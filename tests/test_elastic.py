"""Elastic mesh degradation (docs/SPEC.md §16): device loss shrinks
the mesh and rescues live state instead of killing the job.

Covers the DeviceLostError taxonomy row, the public
``redistribute(container, new_dist)`` API, the rescue/restore/lost
container matrix (per-segment hybrid restore included), the automatic
hooks at every kind of dispatch moment — mid-eager-op (retry),
mid-plan-flush (queue replay), mid-serve-batch (daemon survives, no
client dropped) — the shrink chapter of the degradation story, the
``DR_TPU_SANITIZE=1`` pass over the shrink path, and the 2-process
"killed worker downgrades the mesh, not the job" leg (skipped where
the jaxlib CPU backend lacks multiprocess SPMD, like test_multihost).
"""

import os
import socket
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

import dr_tpu
from dr_tpu.utils import elastic, faults, resilience
from dr_tpu.utils.env import env_int, env_override, env_raw

ITERS = env_int("DR_TPU_FUZZ_ITERS", 28, floor=0)


def _half(x):
    return x * 0.5


# ---------------------------------------------------------------------------
# taxonomy + attribution
# ---------------------------------------------------------------------------

def test_device_lost_classification():
    """Raw backend device-loss text classifies onto DeviceLostError —
    BEFORE the transient bucket (the same messages often carry
    'unavailable', and retrying a dead mesh cannot land)."""
    assert resilience.classify(
        "DEVICE_LOST: chip unavailable") is resilience.DeviceLostError
    assert resilience.classify(
        "DATA_LOSS: hbm contents gone") is resilience.DeviceLostError
    # an injected loss round-trips through classified() keeping rank
    e = resilience.DeviceLostError("x", rank=3)
    assert resilience.classified(e) is e
    assert resilience.classify(e) is resilience.DeviceLostError


def test_attribute_collective_failure():
    """attribute() pins an anonymous collective failure on a rank —
    the DeviceLostError the rescue hooks act on."""
    raw = resilience.TransientBackendError("UNAVAILABLE: peer gone",
                                           site="collectives.shift")
    de = elastic.attribute(raw, 2)
    assert isinstance(de, resilience.DeviceLostError)
    assert de.rank == 2
    assert de.site == "collectives.shift"
    assert de.__cause__ is raw


def test_device_lost_fault_site_registered():
    """The new sites are in the registry with their kinds, so the
    chaos sweep (test_chaos) parametrizes over them automatically."""
    sites = faults.sites()
    assert sites["device.lost"] == ("device_lost",)
    assert set(sites["mesh.shrink"]) == {"transient", "program"}
    with faults.injected("device.lost", "device_lost", times=1):
        with pytest.raises(resilience.DeviceLostError):
            dr_tpu.fill(dr_tpu.distributed_vector(8), 1.0)


# ---------------------------------------------------------------------------
# redistribute (public API)
# ---------------------------------------------------------------------------

def test_redistribute_roundtrip_and_validation():
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    # even -> team -> uneven -> even, value preserved bit-for-bit
    out = dr_tpu.redistribute(v, [n] + [0] * (P - 1))
    assert out is v
    assert v.distribution.sizes[0] == n
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    dr_tpu.redistribute(v, [1] * (P - 1) + [n - (P - 1)])
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    dr_tpu.redistribute(v, None)
    assert v.distribution is None
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    # algorithms keep answering on the new layout
    assert abs(float(dr_tpu.reduce(v)) - src.sum()) < 1e-3
    with pytest.raises(ValueError):
        dr_tpu.redistribute(v, [n])  # wrong shard count
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_redistribute_cross_runtime():
    """Target a SECOND runtime over a device subset — the cross-mesh
    move ROADMAP item 2's collective lowering will accelerate."""
    import jax
    from jax.sharding import Mesh
    from dr_tpu.parallel.runtime import Runtime

    devs = jax.devices()
    if len(devs) < 3:
        pytest.skip("needs >= 3 devices")
    small = Runtime(mesh=Mesh(np.asarray(devs[1:3]), ("x",)))
    src = np.arange(10, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.redistribute(v, [4, 6], runtime=small)
    assert v.runtime is small
    assert v.nshards == 2
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    dr_tpu.redistribute(v, None)  # back onto the global runtime
    assert v.nshards == dr_tpu.nprocs()
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_redistribute_matrix_reblock():
    src = np.arange(24, dtype=np.float32).reshape(6, 4)
    m = dr_tpu.distributed_mdarray.from_array(src)
    dr_tpu.redistribute(m)
    np.testing.assert_array_equal(m.materialize(), src)
    with pytest.raises(ValueError):
        dr_tpu.redistribute(m, [3, 3])  # dists are a vector contract


def test_redistribute_halo_vector():
    """A halo vector re-plans with its bounds intact (uniform layout
    only — the constructor contract holds across the move)."""
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    v = dr_tpu.distributed_vector.from_array(src, halo=hb)
    dr_tpu.redistribute(v, None)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    v.halo().exchange()  # the rebuilt halo controller still works
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


# ---------------------------------------------------------------------------
# the rescue/restore/lost matrix
# ---------------------------------------------------------------------------

def test_rescue_matrix_fates(tmp_path):
    """One shrink, three fates: a team vector off the dead rank is
    RESCUED bit-equal; a checkpointed default vector is RESTORED
    per-segment (survivor windows keep their post-checkpoint writes,
    the dead segment rewinds to the checkpoint); an uncheckpointed
    default vector is LOST and poisoned — any use raises classified."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)

    team = dr_tpu.distributed_vector.from_array(
        src, distribution=[n] + [0] * (P - 1))
    ck = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "ck.npz"), ck)
    ck.put(np.arange(4), np.full(4, 99.0, np.float32))  # rank-0 window
    gone = dr_tpu.distributed_vector.from_array(src * 3)

    rep = elastic.rescue_session(
        resilience.DeviceLostError("test loss", rank=P - 1))
    assert (rep.rescued, rep.restored, rep.lost) == (1, 1, 1)
    assert rep.nprocs_after == P - 1
    assert dr_tpu.nprocs() == P - 1

    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)
    expect = src.copy()
    expect[:4] = 99.0  # survivor keeps its post-checkpoint write
    np.testing.assert_array_equal(dr_tpu.to_numpy(ck), expect)
    with pytest.raises(resilience.DeviceLostError):
        dr_tpu.to_numpy(gone)
    with pytest.raises(resilience.DeviceLostError):
        dr_tpu.fill(gone, 0.0)

    # the story carries the shrink chapter (markers -> detail.degraded)
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] == 1
    assert story["shrink"]["lost_ranks"] == str(P - 1)
    assert story["shrink"]["rescued"] == 1
    # and reset clears it (the conftest hygiene contract)
    elastic.reset()
    assert resilience.degradation_story() is None


def test_rescue_restores_matrix_container(tmp_path):
    """A checkpointed dense matrix restores whole-container (v1) onto
    the shrunken mesh; an uncheckpointed one is poisoned."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    src = np.arange(4 * P * 3, dtype=np.float32).reshape(4 * P, 3)
    m = dr_tpu.dense_matrix.from_array(src, dr_tpu.row_tiles())
    dr_tpu.checkpoint.save(str(tmp_path / "m.npz"), m)
    m2 = dr_tpu.dense_matrix.from_array(src * 2, dr_tpu.row_tiles())
    rep = elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=0))
    assert rep.restored >= 1 and rep.lost >= 1
    np.testing.assert_array_equal(m.materialize(), src)
    with pytest.raises(resilience.DeviceLostError):
        m2.materialize()


def test_min_devices_floor():
    """Below DR_TPU_ELASTIC_MIN_DEVICES the rescue refuses classified
    (never a silent single-device limp-along the operator forbade)."""
    P = dr_tpu.nprocs()
    with env_override(DR_TPU_ELASTIC_MIN_DEVICES=str(P)):
        with pytest.raises(resilience.DeviceLostError):
            elastic.rescue_session(
                resilience.DeviceLostError("loss", rank=0))
    assert dr_tpu.nprocs() == P  # nothing shrank


def test_mesh_shrink_fault_fails_rescue_cleanly():
    """A fault at the mesh.shrink site fails the rescue classified
    with the session untouched — the chaos contract for the new site."""
    P = dr_tpu.nprocs()
    v = dr_tpu.distributed_vector.from_array(
        np.arange(8, dtype=np.float32))
    with faults.injected("mesh.shrink", "transient", times=1):
        with pytest.raises(resilience.TransientBackendError):
            elastic.rescue_session(
                resilience.DeviceLostError("loss", rank=P - 1))
    assert dr_tpu.nprocs() == P
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.arange(8, dtype=np.float32))


# ---------------------------------------------------------------------------
# automatic hooks: mid-eager-op / mid-plan-flush / mid-serve-batch
# ---------------------------------------------------------------------------

def test_eager_retry_shrinks_and_recovers(tmp_path):
    """Mid-eager-op device loss under resilience.retry with elastic
    armed: shrink, per-segment restore, re-run — bit-correct on the
    shrunken mesh."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.random.default_rng(5).standard_normal(n).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "v.npz"), v)
    with env_override(DR_TPU_ELASTIC="1"):
        with faults.injected("device.lost", "device_lost",
                             times=1) as sp:
            resilience.retry(lambda: dr_tpu.sort(v), attempts=2,
                             sleep=lambda s: None)
            assert sp.fired == 1
    assert dr_tpu.nprocs() == P - 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))


def test_eager_loss_without_elastic_is_classified():
    """Elastic off: the loss surfaces classified (no silent shrink),
    and retry does NOT eat it — the pre-elastic contract."""
    P = dr_tpu.nprocs()
    v = dr_tpu.distributed_vector.from_array(
        np.arange(8, dtype=np.float32))
    with faults.injected("device.lost", "device_lost", times=1):
        with pytest.raises(resilience.DeviceLostError):
            resilience.retry(lambda: dr_tpu.sort(v), attempts=3,
                             sleep=lambda s: None)
    assert dr_tpu.nprocs() == P


def test_plan_flush_replay(tmp_path):
    """Mid-plan-flush device loss: the unexecuted queue re-records
    against the shrunken mesh and flushes again — results bit-equal to
    the eager chain, PlanScalar handles resolve, and the plan log
    carries the 'elastic replay' flush."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "v.npz"), v)
    with env_override(DR_TPU_ELASTIC="1"):
        with faults.injected("device.lost", "device_lost", times=1):
            with dr_tpu.deferred() as p:
                dr_tpu.fill(v, 2.0)
                dr_tpu.for_each(v, _half)
                tot = dr_tpu.reduce(v)
    assert float(tot) == n
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.ones(n, np.float32))
    assert dr_tpu.nprocs() == P - 1
    reasons = [e["reason"] for e in p.log]
    assert "elastic replay" in reasons
    assert any(e.get("elastic_replayed") for e in p.log)


def test_plan_flush_loss_without_elastic_drops_queue():
    """Elastic off: a device loss at the flush boundary keeps the
    faulted-flush contract — classified error, unexecuted queue
    dropped, containers untouched, handles break loudly."""
    n = 4 * dr_tpu.nprocs()
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    with faults.injected("device.lost", "device_lost", times=1):
        with pytest.raises(resilience.DeviceLostError):
            with dr_tpu.deferred():
                dr_tpu.fill(v, 2.0)
                tot = dr_tpu.reduce(v)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    with pytest.raises(RuntimeError):
        float(tot)


def test_serve_daemon_survives_device_loss(tmp_path):
    """Mid-serve-batch device loss: the daemon's retry leg shrinks the
    claim and REPLAYS the batch — the live client gets its correct
    answer, later requests keep landing, and stats/degradation story
    carry the shrink."""
    from dr_tpu import serve

    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    with env_override(DR_TPU_ELASTIC="1"):
        srv = serve.Server(str(tmp_path / "el.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(16, dtype=np.float32)
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                faults.inject("device.lost", "device_lost", times=1)
                np.testing.assert_allclose(c.scale(x, a=3.0), x * 3.0,
                                           rtol=1e-6)
                st = c.stats()
                assert st["shrinks"] == 1
                assert "shrunken mesh" in st["degraded"]
                # still serving on the survivors
                assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) \
                    < 1e-4
        finally:
            faults.clear()
            srv.stop()
    assert dr_tpu.nprocs() == P - 1
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] == 1
    assert story["serve"]["reason"].startswith("serve: device loss")


@pytest.mark.parametrize("kind", ["eager", "plan", "serve"])
def test_chaos_device_loss_every_kind(kind, tmp_path):
    """The acceptance sweep shape: an injected device loss at EVERY
    dispatch kind ends in a bit-correct result on the shrunken mesh —
    rescued state equal to the pre-fault oracle — with the shrink
    chapter in the degradation story.  Never a hang, never a silent
    wrong answer (the no-elastic classified leg is covered above)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.random.default_rng(7).standard_normal(n).astype(np.float32)

    def run():
        if kind == "eager":
            v = dr_tpu.distributed_vector.from_array(src)
            dr_tpu.checkpoint.save(str(tmp_path / "c.npz"), v)
            faults.inject("device.lost", "device_lost", times=1)
            resilience.retry(lambda: dr_tpu.sort(v), attempts=2,
                             sleep=lambda s: None)
            return dr_tpu.to_numpy(v), np.sort(src)
        if kind == "plan":
            v = dr_tpu.distributed_vector.from_array(src)
            dr_tpu.checkpoint.save(str(tmp_path / "c.npz"), v)
            faults.inject("device.lost", "device_lost", times=1)
            with dr_tpu.deferred():
                dr_tpu.for_each(v, _half)
            return dr_tpu.to_numpy(v), src * 0.5
        from dr_tpu import serve
        srv = serve.Server(str(tmp_path / "c.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                faults.inject("device.lost", "device_lost", times=1)
                return c.scale(src, a=2.0, b=1.0), src * 2.0 + 1.0
        finally:
            srv.stop()

    with env_override(DR_TPU_ELASTIC="1"):
        try:
            got, want = resilience.with_deadline(run, 120.0,
                                                 site=f"elastic:{kind}",
                                                 dump=False)
        finally:
            faults.clear()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert dr_tpu.nprocs() == P - 1
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] >= 1


# ---------------------------------------------------------------------------
# fuzz: random kill-a-rank over random container populations
# ---------------------------------------------------------------------------

def test_fuzz_elastic_kill_a_rank(tmp_path):
    """fuzz_crank.sh elastic arm: random container populations (team /
    default / checkpointed vectors, uneven distributions, an mdarray),
    a random lost rank, one rescue — every container either matches
    its pre-fault oracle (rescued/restored) or raises classified
    (lost), the report counts add up, and the shrunken session keeps
    computing."""
    import jax

    all_devs = jax.devices()
    if len(all_devs) < 2:
        pytest.skip("shrink needs >= 2 devices")
    # fresh meshes + shrunken meshes recompile per pass: CI runs a
    # slice, the crank sets DR_TPU_FUZZ_ITERS explicitly
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else max(3, ITERS // 6)
    rng = np.random.default_rng(1800)
    for it in range(iters):
        P = int(rng.integers(2, len(all_devs) + 1))
        dr_tpu.init(all_devs[:P])
        elastic.reset()
        lost = int(rng.integers(0, P))
        pop = []  # (container, oracle, may_be_lost)
        for k in range(int(rng.integers(1, 4))):
            n = int(rng.integers(1, 64))
            src = rng.standard_normal(n).astype(np.float32)
            shape = rng.integers(0, 3)
            if shape == 0:  # team distribution dodging a random rank
                sizes = np.zeros(P, np.int64)
                home = int(rng.integers(0, P))
                sizes[home] = n
                c = dr_tpu.distributed_vector.from_array(
                    src, distribution=sizes.tolist())
                pop.append((c, src, home == lost))
            elif shape == 1:  # random uneven cut
                cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
                b = np.concatenate(([0], cuts, [n]))
                sizes = [int(y - x) for x, y in zip(b[:-1], b[1:])]
                c = dr_tpu.distributed_vector.from_array(
                    src, distribution=sizes)
                pop.append((c, src, sizes[lost] > 0))
            else:  # default layout, sometimes checkpointed
                c = dr_tpu.distributed_vector.from_array(src)
                if rng.integers(0, 2):
                    dr_tpu.checkpoint.save(
                        str(tmp_path / f"f{it}_{k}.npz"), c)
                    pop.append((c, src, False))  # restorable
                else:
                    b, e = c._rank_window(lost)
                    pop.append((c, src, b < e))
        rep = elastic.rescue_session(
            resilience.DeviceLostError(f"fuzz kill {it}", rank=lost))
        assert rep.nprocs_after == P - 1
        assert rep.rescued + rep.restored + rep.lost == len(pop)
        survived = 0
        for c, oracle, may_lose in pop:
            try:
                got = dr_tpu.to_numpy(c)
            except resilience.DeviceLostError:
                assert may_lose, "a rescuable container was lost"
                continue
            survived += 1
            np.testing.assert_allclose(got, oracle, rtol=1e-6)
        assert survived == rep.rescued + rep.restored
        # the shrunken session still computes correctly
        w = dr_tpu.distributed_vector.from_array(
            np.ones(2 * (P - 1), np.float32))
        assert abs(float(dr_tpu.reduce(w)) - 2 * (P - 1)) < 1e-4


# ---------------------------------------------------------------------------
# sanitize pass over the shrink path
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parent.parent


def test_sanitize_shrink_subprocess():
    """DR_TPU_SANITIZE=1 over the shrink path: the rebuilt mesh's
    dispatch keys are fresh and canon-portable, and re-running the
    same chain on the shrunken mesh stays within the recompile budget
    (a shrink must not start a value-keyed recompile storm)."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import dr_tpu
from dr_tpu.utils import elastic, resilience, sanitize

assert sanitize.installed()


def _mul(x, c):
    return x * c


dr_tpu.init()
P = dr_tpu.nprocs()
n = 4 * P
src = np.arange(n, dtype=np.float32)
v = dr_tpu.distributed_vector.from_array(
    src, distribution=[n] + [0] * (P - 1))
sanitize.reset_epoch()
elastic.rescue_session(resilience.DeviceLostError("smoke", rank=P - 1))
assert dr_tpu.nprocs() == P - 1
np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
a = dr_tpu.distributed_vector(n, np.float32)
dr_tpu.fill(a, 2.0)
dr_tpu.transform(a, a, _mul, 3.0)
assert float(dr_tpu.reduce(a)) == 6.0 * n
# the same chain again on the SHRUNKEN mesh must be cache-warm
with sanitize.zero_recompile("post-shrink re-run"):
    dr_tpu.fill(a, 4.0)
    dr_tpu.transform(a, a, _mul, 5.0)
    assert float(dr_tpu.reduce(a)) == 20.0 * n
sanitize.check_recompiles()
print("SANITIZED-SHRINK-OK")
"""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", DR_TPU_SANITIZE="1",
               DR_TPU_SILENCE_FALLBACKS="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=str(REPO) + os.pathsep
               + env.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SANITIZED-SHRINK-OK" in r.stdout


# ---------------------------------------------------------------------------
# 2-process leg: a killed worker downgrades the mesh, not the job
# ---------------------------------------------------------------------------

WORKER = Path(__file__).resolve().parent / "elastic_worker.py"
_BACKEND_CANT = "Multiprocess computations aren't implemented"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_multihost_killed_worker_downgrades_mesh(tmp_path):
    """Two processes join a distributed mesh; worker 1 is KILLED
    mid-run.  Worker 0 attributes the collective failure to the dead
    rank (elastic.attribute), downgrades to its local devices, restores
    the checkpointed state, and finishes — the job survives the host
    loss.  Skips where the jaxlib CPU backend lacks multiprocess SPMD
    (the same toolchain gate as test_multihost)."""
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = ""  # one local device per process
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH",
                                                         "")
    ck = str(tmp_path / "mh_elastic.npz")
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port), ck],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for pid in range(2)
    ]
    outs = [None, None]

    def drain(i, p):
        outs[i], _ = p.communicate()

    threads = [threading.Thread(target=drain, args=(i, p))
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    import time
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if procs[0].poll() is not None:
            break
        time.sleep(0.5)
    for p in procs:
        if p.poll() is None:
            p.kill()
    for t in threads:
        t.join(timeout=30)
    blob = "".join(o or "" for o in outs)
    if _BACKEND_CANT in blob:
        pytest.skip("jaxlib CPU backend lacks multiprocess SPMD "
                    "(toolchain capability, not a code property)")
    # worker 1 self-kills by design; worker 0 must survive and finish
    assert procs[0].returncode == 0, (outs[0] or "")[-2000:]
    assert "ELASTIC-MULTIHOST-OK" in (outs[0] or "")


# ---------------------------------------------------------------------------
# review-fix regressions (round 13)
# ---------------------------------------------------------------------------

def test_failed_redistribute_leaves_vector_intact():
    """A rejected redistribute (bad sizes for the TARGET runtime) must
    leave a live vector exactly as it was — no half-rebound mix of two
    layouts (validation runs before any attribute commits)."""
    import jax
    from jax.sharding import Mesh
    from dr_tpu.parallel.runtime import Runtime

    devs = jax.devices()
    if len(devs) < 3:
        pytest.skip("needs >= 3 devices")
    src = np.arange(12, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    P = v.nshards
    small = Runtime(mesh=Mesh(np.asarray(devs[:2]), ("x",)))
    with pytest.raises(ValueError):
        dr_tpu.redistribute(v, [12] + [0] * (P - 1), runtime=small)
    assert v.nshards == P and v.runtime is not small
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    assert abs(float(dr_tpu.reduce(v)) - src.sum()) < 1e-3


def test_gather_failure_falls_back_to_checkpoint(tmp_path):
    """A second fault striking the rescue GATHER must not poison a
    checkpointed container: the fate degrades rescue -> restore, not
    rescue -> lost (§16.3: lost means NO checkpoint)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    src = np.arange(3 * P, dtype=np.float32)
    team = dr_tpu.distributed_vector.from_array(
        src, distribution=[len(src)] + [0] * (P - 1))
    dr_tpu.checkpoint.save(str(tmp_path / "g.npz"), team)
    # the next dispatch-tap visit is the rescue's snapshot gather
    with faults.injected("device.lost", "device_lost", times=1):
        rep = elastic.rescue_session(
            resilience.DeviceLostError("loss", rank=P - 1))
    assert (rep.rescued, rep.restored, rep.lost) == (0, 1, 0), rep
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)


def test_invalid_rank_attribution_raises():
    """A stale/out-of-range rank attribution fails loudly instead of
    silently shrinking the wrong rank."""
    P = dr_tpu.nprocs()
    with pytest.raises(resilience.ProgramError):
        elastic.rescue_session(lost_ranks=[P + 5])
    with pytest.raises(resilience.ProgramError):
        elastic.rescue_session(
            resilience.DeviceLostError("stale", rank=P))
    assert dr_tpu.nprocs() == P


def test_checkpoint_registry_prunes_dead_containers(tmp_path):
    """The elastic checkpoint registry stays bounded: a collected
    container's row is pruned by the weakref death callback."""
    import gc

    before = len(elastic._ckpts)
    v = dr_tpu.distributed_vector.from_array(
        np.arange(8, dtype=np.float32))
    dr_tpu.checkpoint.save(str(tmp_path / "p.npz"), v)
    assert len(elastic._ckpts) == before + 1
    assert elastic.checkpoint_path(v) is not None
    del v
    gc.collect()
    assert len(elastic._ckpts) == before


def test_serve_shrink_recorded_even_when_replay_fails(tmp_path):
    """A shrink whose REPLAY then fails still changed the resident
    claim: stats()['shrinks'] and the degraded marker must record it
    (detection lives in the dispatch finally, not the success path)."""
    from dr_tpu import serve

    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    with env_override(DR_TPU_ELASTIC="1"):
        srv = serve.Server(str(tmp_path / "sf.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(8, dtype=np.float32)
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                # attempt 1: clean serve.flush visit, then the loss;
                # attempt 2 (the replay): a deterministic fault fails
                # the batch AFTER the shrink already happened
                faults.inject("device.lost", "device_lost", times=1)
                faults.inject("serve.flush", "program", after=1)
                with pytest.raises(resilience.ResilienceError):
                    c.scale(x, a=3.0)
                faults.clear()
                st = c.stats()
                assert st["shrinks"] == 1, st
                assert "shrunken mesh" in (st["degraded"] or ""), st
                # and the daemon keeps serving on the survivors
                np.testing.assert_allclose(c.scale(x, a=4.0), x * 4.0,
                                           rtol=1e-6)
        finally:
            faults.clear()
            srv.stop()
