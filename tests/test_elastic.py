"""Elastic mesh degradation (docs/SPEC.md §16): device loss shrinks
the mesh and rescues live state instead of killing the job.

Covers the DeviceLostError taxonomy row, the public
``redistribute(container, new_dist)`` API, the rescue/restore/lost
container matrix (per-segment hybrid restore included), the automatic
hooks at every kind of dispatch moment — mid-eager-op (retry),
mid-plan-flush (queue replay), mid-serve-batch (daemon survives, no
client dropped) — the shrink chapter of the degradation story, the
``DR_TPU_SANITIZE=1`` pass over the shrink path, and the 2-process
"killed worker downgrades the mesh, not the job" leg (skipped where
the jaxlib CPU backend lacks multiprocess SPMD, like test_multihost).
"""

import os
import socket
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

import dr_tpu
from dr_tpu.utils import elastic, faults, resilience
from dr_tpu.utils.env import env_int, env_override, env_raw

ITERS = env_int("DR_TPU_FUZZ_ITERS", 28, floor=0)


def _half(x):
    return x * 0.5


# ---------------------------------------------------------------------------
# taxonomy + attribution
# ---------------------------------------------------------------------------

def test_device_lost_classification():
    """Raw backend device-loss text classifies onto DeviceLostError —
    BEFORE the transient bucket (the same messages often carry
    'unavailable', and retrying a dead mesh cannot land)."""
    assert resilience.classify(
        "DEVICE_LOST: chip unavailable") is resilience.DeviceLostError
    assert resilience.classify(
        "DATA_LOSS: hbm contents gone") is resilience.DeviceLostError
    # an injected loss round-trips through classified() keeping rank
    e = resilience.DeviceLostError("x", rank=3)
    assert resilience.classified(e) is e
    assert resilience.classify(e) is resilience.DeviceLostError


def test_attribute_collective_failure():
    """attribute() pins an anonymous collective failure on a rank —
    the DeviceLostError the rescue hooks act on."""
    raw = resilience.TransientBackendError("UNAVAILABLE: peer gone",
                                           site="collectives.shift")
    de = elastic.attribute(raw, 2)
    assert isinstance(de, resilience.DeviceLostError)
    assert de.rank == 2
    assert de.site == "collectives.shift"
    assert de.__cause__ is raw


def test_device_lost_fault_site_registered():
    """The new sites are in the registry with their kinds, so the
    chaos sweep (test_chaos) parametrizes over them automatically."""
    sites = faults.sites()
    assert sites["device.lost"] == ("device_lost",)
    assert set(sites["mesh.shrink"]) == {"transient", "program"}
    with faults.injected("device.lost", "device_lost", times=1):
        with pytest.raises(resilience.DeviceLostError):
            dr_tpu.fill(dr_tpu.distributed_vector(8), 1.0)


# ---------------------------------------------------------------------------
# redistribute (public API)
# ---------------------------------------------------------------------------

def test_redistribute_roundtrip_and_validation():
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    # even -> team -> uneven -> even, value preserved bit-for-bit
    out = dr_tpu.redistribute(v, [n] + [0] * (P - 1))
    assert out is v
    assert v.distribution.sizes[0] == n
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    dr_tpu.redistribute(v, [1] * (P - 1) + [n - (P - 1)])
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    dr_tpu.redistribute(v, None)
    assert v.distribution is None
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    # algorithms keep answering on the new layout
    assert abs(float(dr_tpu.reduce(v)) - src.sum()) < 1e-3
    with pytest.raises(ValueError):
        dr_tpu.redistribute(v, [n])  # wrong shard count
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_redistribute_cross_runtime():
    """Target a SECOND runtime over a device subset — the cross-mesh
    move ROADMAP item 2's collective lowering will accelerate."""
    import jax
    from jax.sharding import Mesh
    from dr_tpu.parallel.runtime import Runtime

    devs = jax.devices()
    if len(devs) < 3:
        pytest.skip("needs >= 3 devices")
    small = Runtime(mesh=Mesh(np.asarray(devs[1:3]), ("x",)))
    src = np.arange(10, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.redistribute(v, [4, 6], runtime=small)
    assert v.runtime is small
    assert v.nshards == 2
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    dr_tpu.redistribute(v, None)  # back onto the global runtime
    assert v.nshards == dr_tpu.nprocs()
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_redistribute_matrix_reblock():
    src = np.arange(24, dtype=np.float32).reshape(6, 4)
    m = dr_tpu.distributed_mdarray.from_array(src)
    dr_tpu.redistribute(m)
    np.testing.assert_array_equal(m.materialize(), src)
    with pytest.raises(ValueError):
        dr_tpu.redistribute(m, [3, 3])  # dists are a vector contract


def test_redistribute_halo_vector():
    """A halo vector re-plans with its bounds intact (uniform layout
    only — the constructor contract holds across the move)."""
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    v = dr_tpu.distributed_vector.from_array(src, halo=hb)
    dr_tpu.redistribute(v, None)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    v.halo().exchange()  # the rebuilt halo controller still works
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


# ---------------------------------------------------------------------------
# the rescue/restore/lost matrix
# ---------------------------------------------------------------------------

def test_rescue_matrix_fates(tmp_path):
    """One shrink, three fates: a team vector off the dead rank is
    RESCUED bit-equal; a checkpointed default vector is RESTORED
    per-segment (survivor windows keep their post-checkpoint writes,
    the dead segment rewinds to the checkpoint); an uncheckpointed
    default vector is LOST and poisoned — any use raises classified."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)

    team = dr_tpu.distributed_vector.from_array(
        src, distribution=[n] + [0] * (P - 1))
    ck = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "ck.npz"), ck)
    ck.put(np.arange(4), np.full(4, 99.0, np.float32))  # rank-0 window
    gone = dr_tpu.distributed_vector.from_array(src * 3)

    rep = elastic.rescue_session(
        resilience.DeviceLostError("test loss", rank=P - 1))
    assert (rep.rescued, rep.restored, rep.lost) == (1, 1, 1)
    assert rep.nprocs_after == P - 1
    assert dr_tpu.nprocs() == P - 1

    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)
    expect = src.copy()
    expect[:4] = 99.0  # survivor keeps its post-checkpoint write
    np.testing.assert_array_equal(dr_tpu.to_numpy(ck), expect)
    with pytest.raises(resilience.DeviceLostError):
        dr_tpu.to_numpy(gone)
    with pytest.raises(resilience.DeviceLostError):
        dr_tpu.fill(gone, 0.0)

    # the story carries the shrink chapter (markers -> detail.degraded)
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] == 1
    assert story["shrink"]["lost_ranks"] == str(P - 1)
    assert story["shrink"]["rescued"] == 1
    # and reset clears it (the conftest hygiene contract)
    elastic.reset()
    assert resilience.degradation_story() is None


def test_rescue_restores_matrix_container(tmp_path):
    """A checkpointed dense matrix restores whole-container (v1) onto
    the shrunken mesh; an uncheckpointed one is poisoned."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    src = np.arange(4 * P * 3, dtype=np.float32).reshape(4 * P, 3)
    m = dr_tpu.dense_matrix.from_array(src, dr_tpu.row_tiles())
    dr_tpu.checkpoint.save(str(tmp_path / "m.npz"), m)
    m2 = dr_tpu.dense_matrix.from_array(src * 2, dr_tpu.row_tiles())
    rep = elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=0))
    assert rep.restored >= 1 and rep.lost >= 1
    np.testing.assert_array_equal(m.materialize(), src)
    with pytest.raises(resilience.DeviceLostError):
        m2.materialize()


def test_min_devices_floor():
    """Below DR_TPU_ELASTIC_MIN_DEVICES the rescue refuses classified
    (never a silent single-device limp-along the operator forbade)."""
    P = dr_tpu.nprocs()
    with env_override(DR_TPU_ELASTIC_MIN_DEVICES=str(P)):
        with pytest.raises(resilience.DeviceLostError):
            elastic.rescue_session(
                resilience.DeviceLostError("loss", rank=0))
    assert dr_tpu.nprocs() == P  # nothing shrank


def test_mesh_shrink_fault_fails_rescue_cleanly():
    """A fault at the mesh.shrink site fails the rescue classified
    with the session untouched — the chaos contract for the new site."""
    P = dr_tpu.nprocs()
    v = dr_tpu.distributed_vector.from_array(
        np.arange(8, dtype=np.float32))
    with faults.injected("mesh.shrink", "transient", times=1):
        with pytest.raises(resilience.TransientBackendError):
            elastic.rescue_session(
                resilience.DeviceLostError("loss", rank=P - 1))
    assert dr_tpu.nprocs() == P
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.arange(8, dtype=np.float32))


# ---------------------------------------------------------------------------
# automatic hooks: mid-eager-op / mid-plan-flush / mid-serve-batch
# ---------------------------------------------------------------------------

def test_eager_retry_shrinks_and_recovers(tmp_path):
    """Mid-eager-op device loss under resilience.retry with elastic
    armed: shrink, per-segment restore, re-run — bit-correct on the
    shrunken mesh."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.random.default_rng(5).standard_normal(n).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "v.npz"), v)
    with env_override(DR_TPU_ELASTIC="1"):
        with faults.injected("device.lost", "device_lost",
                             times=1) as sp:
            resilience.retry(lambda: dr_tpu.sort(v), attempts=2,
                             sleep=lambda s: None)
            assert sp.fired == 1
    assert dr_tpu.nprocs() == P - 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))


def test_eager_loss_without_elastic_is_classified():
    """Elastic off: the loss surfaces classified (no silent shrink),
    and retry does NOT eat it — the pre-elastic contract."""
    P = dr_tpu.nprocs()
    v = dr_tpu.distributed_vector.from_array(
        np.arange(8, dtype=np.float32))
    with faults.injected("device.lost", "device_lost", times=1):
        with pytest.raises(resilience.DeviceLostError):
            resilience.retry(lambda: dr_tpu.sort(v), attempts=3,
                             sleep=lambda s: None)
    assert dr_tpu.nprocs() == P


def test_plan_flush_replay(tmp_path):
    """Mid-plan-flush device loss: the unexecuted queue re-records
    against the shrunken mesh and flushes again — results bit-equal to
    the eager chain, PlanScalar handles resolve, and the plan log
    carries the 'elastic replay' flush."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "v.npz"), v)
    with env_override(DR_TPU_ELASTIC="1"):
        with faults.injected("device.lost", "device_lost", times=1):
            with dr_tpu.deferred() as p:
                dr_tpu.fill(v, 2.0)
                dr_tpu.for_each(v, _half)
                tot = dr_tpu.reduce(v)
    assert float(tot) == n
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.ones(n, np.float32))
    assert dr_tpu.nprocs() == P - 1
    reasons = [e["reason"] for e in p.log]
    assert "elastic replay" in reasons
    assert any(e.get("elastic_replayed") for e in p.log)


def test_plan_flush_loss_without_elastic_drops_queue():
    """Elastic off: a device loss at the flush boundary keeps the
    faulted-flush contract — classified error, unexecuted queue
    dropped, containers untouched, handles break loudly."""
    n = 4 * dr_tpu.nprocs()
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    with faults.injected("device.lost", "device_lost", times=1):
        with pytest.raises(resilience.DeviceLostError):
            with dr_tpu.deferred():
                dr_tpu.fill(v, 2.0)
                tot = dr_tpu.reduce(v)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    with pytest.raises(RuntimeError):
        float(tot)


def test_serve_daemon_survives_device_loss(tmp_path):
    """Mid-serve-batch device loss: the daemon's retry leg shrinks the
    claim and REPLAYS the batch — the live client gets its correct
    answer, later requests keep landing, and stats/degradation story
    carry the shrink."""
    from dr_tpu import serve

    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    with env_override(DR_TPU_ELASTIC="1"):
        srv = serve.Server(str(tmp_path / "el.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(16, dtype=np.float32)
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                faults.inject("device.lost", "device_lost", times=1)
                np.testing.assert_allclose(c.scale(x, a=3.0), x * 3.0,
                                           rtol=1e-6)
                st = c.stats()
                assert st["shrinks"] == 1
                assert "shrunken mesh" in st["degraded"]
                # still serving on the survivors
                assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) \
                    < 1e-4
        finally:
            faults.clear()
            srv.stop()
    assert dr_tpu.nprocs() == P - 1
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] == 1
    assert story["serve"]["reason"].startswith("serve: device loss")


@pytest.mark.parametrize("kind", ["eager", "plan", "serve"])
def test_chaos_device_loss_every_kind(kind, tmp_path):
    """The acceptance sweep shape: an injected device loss at EVERY
    dispatch kind ends in a bit-correct result on the shrunken mesh —
    rescued state equal to the pre-fault oracle — with the shrink
    chapter in the degradation story.  Never a hang, never a silent
    wrong answer (the no-elastic classified leg is covered above)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.random.default_rng(7).standard_normal(n).astype(np.float32)

    def run():
        if kind == "eager":
            v = dr_tpu.distributed_vector.from_array(src)
            dr_tpu.checkpoint.save(str(tmp_path / "c.npz"), v)
            faults.inject("device.lost", "device_lost", times=1)
            resilience.retry(lambda: dr_tpu.sort(v), attempts=2,
                             sleep=lambda s: None)
            return dr_tpu.to_numpy(v), np.sort(src)
        if kind == "plan":
            v = dr_tpu.distributed_vector.from_array(src)
            dr_tpu.checkpoint.save(str(tmp_path / "c.npz"), v)
            faults.inject("device.lost", "device_lost", times=1)
            with dr_tpu.deferred():
                dr_tpu.for_each(v, _half)
            return dr_tpu.to_numpy(v), src * 0.5
        from dr_tpu import serve
        srv = serve.Server(str(tmp_path / "c.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                faults.inject("device.lost", "device_lost", times=1)
                return c.scale(src, a=2.0, b=1.0), src * 2.0 + 1.0
        finally:
            srv.stop()

    with env_override(DR_TPU_ELASTIC="1"):
        try:
            got, want = resilience.with_deadline(run, 120.0,
                                                 site=f"elastic:{kind}",
                                                 dump=False)
        finally:
            faults.clear()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert dr_tpu.nprocs() == P - 1
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] >= 1


# ---------------------------------------------------------------------------
# fuzz: random kill-a-rank over random container populations
# ---------------------------------------------------------------------------

def test_fuzz_elastic_kill_a_rank(tmp_path):
    """fuzz_crank.sh elastic arm: random container populations (team /
    default / checkpointed vectors, uneven distributions, an mdarray),
    a random lost rank, one rescue — every container either matches
    its pre-fault oracle (rescued/restored) or raises classified
    (lost), the report counts add up, and the shrunken session keeps
    computing."""
    import jax

    all_devs = jax.devices()
    if len(all_devs) < 2:
        pytest.skip("shrink needs >= 2 devices")
    # fresh meshes + shrunken meshes recompile per pass: CI runs a
    # slice, the crank sets DR_TPU_FUZZ_ITERS explicitly
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else max(3, ITERS // 6)
    rng = np.random.default_rng(1800)
    for it in range(iters):
        P = int(rng.integers(2, len(all_devs) + 1))
        dr_tpu.init(all_devs[:P])
        elastic.reset()
        lost = int(rng.integers(0, P))
        pop = []  # (container, oracle, may_be_lost)
        for k in range(int(rng.integers(1, 4))):
            n = int(rng.integers(1, 64))
            src = rng.standard_normal(n).astype(np.float32)
            shape = rng.integers(0, 3)
            if shape == 0:  # team distribution dodging a random rank
                sizes = np.zeros(P, np.int64)
                home = int(rng.integers(0, P))
                sizes[home] = n
                c = dr_tpu.distributed_vector.from_array(
                    src, distribution=sizes.tolist())
                pop.append((c, src, home == lost))
            elif shape == 1:  # random uneven cut
                cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
                b = np.concatenate(([0], cuts, [n]))
                sizes = [int(y - x) for x, y in zip(b[:-1], b[1:])]
                c = dr_tpu.distributed_vector.from_array(
                    src, distribution=sizes)
                pop.append((c, src, sizes[lost] > 0))
            else:  # default layout, sometimes checkpointed
                c = dr_tpu.distributed_vector.from_array(src)
                if rng.integers(0, 2):
                    dr_tpu.checkpoint.save(
                        str(tmp_path / f"f{it}_{k}.npz"), c)
                    pop.append((c, src, False))  # restorable
                else:
                    b, e = c._rank_window(lost)
                    pop.append((c, src, b < e))
        rep = elastic.rescue_session(
            resilience.DeviceLostError(f"fuzz kill {it}", rank=lost))
        assert rep.nprocs_after == P - 1
        assert rep.rescued + rep.restored + rep.lost == len(pop)
        survived = 0
        for c, oracle, may_lose in pop:
            try:
                got = dr_tpu.to_numpy(c)
            except resilience.DeviceLostError:
                assert may_lose, "a rescuable container was lost"
                continue
            survived += 1
            np.testing.assert_allclose(got, oracle, rtol=1e-6)
        assert survived == rep.rescued + rep.restored
        # the shrunken session still computes correctly
        w = dr_tpu.distributed_vector.from_array(
            np.ones(2 * (P - 1), np.float32))
        assert abs(float(dr_tpu.reduce(w)) - 2 * (P - 1)) < 1e-4


# ---------------------------------------------------------------------------
# sanitize pass over the shrink path
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parent.parent


def test_sanitize_shrink_subprocess():
    """DR_TPU_SANITIZE=1 over the shrink AND grow-back paths: the
    rebuilt meshes' dispatch keys are fresh and canon-portable, and
    re-running the same chain on the shrunken (then re-grown) mesh
    stays within the recompile budget (neither a shrink nor a grow may
    start a value-keyed recompile storm)."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import dr_tpu
from dr_tpu.utils import elastic, resilience, sanitize

assert sanitize.installed()


def _mul(x, c):
    return x * c


dr_tpu.init()
P = dr_tpu.nprocs()
n = 4 * P
src = np.arange(n, dtype=np.float32)
v = dr_tpu.distributed_vector.from_array(
    src, distribution=[n] + [0] * (P - 1))
sanitize.reset_epoch()
elastic.rescue_session(resilience.DeviceLostError("smoke", rank=P - 1))
assert dr_tpu.nprocs() == P - 1
np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
a = dr_tpu.distributed_vector(n, np.float32)
dr_tpu.fill(a, 2.0)
dr_tpu.transform(a, a, _mul, 3.0)
assert float(dr_tpu.reduce(a)) == 6.0 * n
# the same chain again on the SHRUNKEN mesh must be cache-warm
with sanitize.zero_recompile("post-shrink re-run"):
    dr_tpu.fill(a, 4.0)
    dr_tpu.transform(a, a, _mul, 5.0)
    assert float(dr_tpu.reduce(a)) == 20.0 * n
sanitize.check_recompiles()
# grow back (SPEC SS16.6): fresh keys on the grown mesh, then the same
# chain re-run must be cache-warm there too
sanitize.reset_epoch()
gr = elastic.grow_session(reason="sanitize grow smoke")
assert gr.nprocs_after == P and dr_tpu.nprocs() == P
np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
dr_tpu.fill(a, 2.0)
dr_tpu.transform(a, a, _mul, 3.0)
assert float(dr_tpu.reduce(a)) == 6.0 * n
with sanitize.zero_recompile("post-grow re-run"):
    dr_tpu.fill(a, 4.0)
    dr_tpu.transform(a, a, _mul, 5.0)
    assert float(dr_tpu.reduce(a)) == 20.0 * n
sanitize.check_recompiles()
print("SANITIZED-SHRINK-OK")
"""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", DR_TPU_SANITIZE="1",
               DR_TPU_SILENCE_FALLBACKS="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=str(REPO) + os.pathsep
               + env.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SANITIZED-SHRINK-OK" in r.stdout


# ---------------------------------------------------------------------------
# 2-process leg: a killed worker downgrades the mesh, not the job
# ---------------------------------------------------------------------------

WORKER = Path(__file__).resolve().parent / "elastic_worker.py"
_BACKEND_CANT = "Multiprocess computations aren't implemented"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_multihost_killed_worker_downgrades_mesh(tmp_path):
    """Two processes join a distributed mesh; worker 1 is KILLED
    mid-run.  Worker 0 attributes the collective failure to the dead
    rank (elastic.attribute), downgrades to its local devices, restores
    the checkpointed state, and finishes — the job survives the host
    loss.  Skips where the jaxlib CPU backend lacks multiprocess SPMD
    (the same toolchain gate as test_multihost)."""
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = ""  # one local device per process
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH",
                                                         "")
    ck = str(tmp_path / "mh_elastic.npz")
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port), ck],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for pid in range(2)
    ]
    outs = [None, None]

    def drain(i, p):
        outs[i], _ = p.communicate()

    threads = [threading.Thread(target=drain, args=(i, p))
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    import time
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if procs[0].poll() is not None:
            break
        time.sleep(0.5)
    for p in procs:
        if p.poll() is None:
            p.kill()
    for t in threads:
        t.join(timeout=30)
    blob = "".join(o or "" for o in outs)
    if _BACKEND_CANT in blob:
        pytest.skip("jaxlib CPU backend lacks multiprocess SPMD "
                    "(toolchain capability, not a code property)")
    # worker 1 self-kills by design; worker 0 must survive and finish
    assert procs[0].returncode == 0, (outs[0] or "")[-2000:]
    assert "ELASTIC-MULTIHOST-OK" in (outs[0] or "")


# ---------------------------------------------------------------------------
# review-fix regressions (round 13)
# ---------------------------------------------------------------------------

def test_failed_redistribute_leaves_vector_intact():
    """A rejected redistribute (bad sizes for the TARGET runtime) must
    leave a live vector exactly as it was — no half-rebound mix of two
    layouts (validation runs before any attribute commits)."""
    import jax
    from jax.sharding import Mesh
    from dr_tpu.parallel.runtime import Runtime

    devs = jax.devices()
    if len(devs) < 3:
        pytest.skip("needs >= 3 devices")
    src = np.arange(12, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    P = v.nshards
    small = Runtime(mesh=Mesh(np.asarray(devs[:2]), ("x",)))
    with pytest.raises(ValueError):
        dr_tpu.redistribute(v, [12] + [0] * (P - 1), runtime=small)
    assert v.nshards == P and v.runtime is not small
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    assert abs(float(dr_tpu.reduce(v)) - src.sum()) < 1e-3


def test_gather_failure_falls_back_to_checkpoint(tmp_path):
    """A second fault striking the rescue GATHER must not poison a
    checkpointed container: the fate degrades rescue -> restore, not
    rescue -> lost (§16.3: lost means NO checkpoint)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    src = np.arange(3 * P, dtype=np.float32)
    team = dr_tpu.distributed_vector.from_array(
        src, distribution=[len(src)] + [0] * (P - 1))
    dr_tpu.checkpoint.save(str(tmp_path / "g.npz"), team)
    # the next dispatch-tap visit is the rescue's snapshot gather
    with faults.injected("device.lost", "device_lost", times=1):
        rep = elastic.rescue_session(
            resilience.DeviceLostError("loss", rank=P - 1))
    assert (rep.rescued, rep.restored, rep.lost) == (0, 1, 0), rep
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)


def test_invalid_rank_attribution_raises():
    """A stale/out-of-range rank attribution fails loudly instead of
    silently shrinking the wrong rank."""
    P = dr_tpu.nprocs()
    with pytest.raises(resilience.ProgramError):
        elastic.rescue_session(lost_ranks=[P + 5])
    with pytest.raises(resilience.ProgramError):
        elastic.rescue_session(
            resilience.DeviceLostError("stale", rank=P))
    assert dr_tpu.nprocs() == P


def test_checkpoint_registry_prunes_dead_containers(tmp_path):
    """The elastic checkpoint registry stays bounded: a collected
    container's row is pruned by the weakref death callback."""
    import gc

    before = len(elastic._ckpts)
    v = dr_tpu.distributed_vector.from_array(
        np.arange(8, dtype=np.float32))
    dr_tpu.checkpoint.save(str(tmp_path / "p.npz"), v)
    assert len(elastic._ckpts) == before + 1
    assert elastic.checkpoint_path(v) is not None
    del v
    gc.collect()
    assert len(elastic._ckpts) == before


# ---------------------------------------------------------------------------
# grow-back: re-admit recovered devices and relays (round 15, SPEC §16.6)
# ---------------------------------------------------------------------------

def test_grow_sites_registered():
    """The two new sites are in the registry with their kinds, so the
    chaos sweep parametrizes over them automatically."""
    sites = faults.sites()
    assert set(sites["device.recover"]) == {"transient", "program"}
    assert set(sites["mesh.grow"]) == {"transient", "program"}


def test_grow_session_roundtrip(tmp_path):
    """shrink → grow: rescued/restored state rides the re-admission
    bit-equal, the mesh is whole again, the degradation story carries
    BOTH chapters, and a container the shrink poisoned stays poisoned
    — a grow never resurrects lost state as a silent wrong answer."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    team = dr_tpu.distributed_vector.from_array(
        src, distribution=[n] + [0] * (P - 1))
    ck = dr_tpu.distributed_vector.from_array(src * 2)
    dr_tpu.checkpoint.save(str(tmp_path / "g.npz"), ck)
    gone = dr_tpu.distributed_vector.from_array(src * 3)
    elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=P - 1))
    assert dr_tpu.nprocs() == P - 1

    rep = elastic.grow_session(reason="rank returned")
    assert isinstance(rep, elastic.GrowReport)
    assert rep.nprocs_before == P - 1 and rep.nprocs_after == P
    assert dr_tpu.nprocs() == P
    assert rep.moved == 2 and rep.kept == 0
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)
    np.testing.assert_array_equal(dr_tpu.to_numpy(ck), src * 2)
    with pytest.raises(resilience.DeviceLostError):
        dr_tpu.to_numpy(gone)
    # the session computes on the grown mesh
    assert abs(float(dr_tpu.reduce(team)) - src.sum()) < 1e-3
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] == 1
    assert story["grow"]["grows"] == 1
    assert story["grow"]["moved"] == 2
    assert story["grow"]["nprocs"] == P
    # and reset clears the grow chapter too (conftest hygiene)
    elastic.reset()
    assert resilience.degradation_story() is None


def test_grow_session_refuses_nothing_to_admit():
    """A full mesh has nothing to re-admit: the probe-driven grow
    refuses classified (and ``require_growth`` rejects a same-size
    target), session untouched."""
    P = dr_tpu.nprocs()
    with pytest.raises(resilience.ProgramError):
        elastic.grow_session()
    with pytest.raises(resilience.ProgramError):
        elastic.grow_session(devices=dr_tpu.devices())
    assert dr_tpu.nprocs() == P


def test_mesh_grow_fault_never_makes_worse():
    """A fault at the mesh.grow site fails the re-admission classified
    with the session STILL SERVING on the small mesh — the chaos
    contract for the new site (grow must never make things worse)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    team = dr_tpu.distributed_vector.from_array(
        src, distribution=[n] + [0] * (P - 1))
    elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=P - 1))
    with faults.injected("mesh.grow", "transient", times=1):
        with pytest.raises(resilience.TransientBackendError):
            elastic.grow_session()
    assert dr_tpu.nprocs() == P - 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)
    assert elastic.grow_count() == 0
    # a later clean grow still works
    rep = elastic.grow_session()
    assert rep.nprocs_after == P and dr_tpu.nprocs() == P
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), src)


def test_device_recover_fault_classified():
    """An injected fault at the recovery probe surfaces classified
    from the probe-driven grow, and the polled supervisor absorbs it
    (warn + backoff, never a raise)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=P - 1))
    with faults.injected("device.recover", "program", times=1):
        with pytest.raises(resilience.ProgramError):
            elastic.grow_session()
    assert dr_tpu.nprocs() == P - 1
    # the supervisor path never raises: poll absorbs the classified
    # fault and the session stays put
    with env_override(DR_TPU_ELASTIC_GROW="1",
                      DR_TPU_ELASTIC_GROW_PROBE_S="0"):
        with faults.injected("device.recover", "transient", times=1):
            assert elastic.maybe_grow() is None
        assert dr_tpu.nprocs() == P - 1
        # next poll (fault exhausted) completes the grow-back
        rep = elastic.maybe_grow()
        assert rep is not None and dr_tpu.nprocs() == P


def test_grow_supervisor_bounded_backoff():
    """The supervisor is bounded and deterministic: delays ride the
    seeded backoff schedule, the probe budget caps total probes, and a
    classified attempt failure is absorbed (counted, warned)."""
    with env_override(DR_TPU_ELASTIC_GROW_PROBE_S="0.05",
                      DR_TPU_ELASTIC_GROW_PROBE_CAP_S="0.2",
                      DR_TPU_ELASTIC_GROW_PROBES="3"):
        sup = elastic.GrowSupervisor()
        assert sup.budget == 3
        assert not sup.due(now=0.0)  # first probe waits one base delay

        def boom():
            raise resilience.TransientBackendError("probe died")

        import time as _t
        deadline = _t.monotonic() + 10.0
        while not sup.exhausted() and _t.monotonic() < deadline:
            sup.poll(boom)
            _t.sleep(0.005)
        assert sup.exhausted() and sup.probes == 3
        assert sup.failures == 3
        # exhausted: no more probes, ever
        assert sup.poll(boom) is None
        assert sup.probes == 3


def test_plan_region_exit_polls_growback(tmp_path):
    """The between-flushes hook: a device loss mid-flush shrinks the
    mesh (elastic replay), and the NEXT deferred-region exit polls the
    grow supervisor and re-admits the returned device — results
    bit-equal throughout, no explicit grow call anywhere."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "v.npz"), v)
    with env_override(DR_TPU_ELASTIC="1", DR_TPU_ELASTIC_GROW="1",
                      DR_TPU_ELASTIC_GROW_PROBE_S="0"):
        with faults.injected("device.lost", "device_lost", times=1):
            with dr_tpu.deferred():
                dr_tpu.fill(v, 2.0)
                dr_tpu.for_each(v, _half)
        # the loss shrank the mesh; the region-exit poll follows the
        # shrink within the same exit (delay 0) or the next region
        assert dr_tpu.nprocs() in (P - 1, P)
        with dr_tpu.deferred():
            dr_tpu.for_each(v, _half)
    assert dr_tpu.nprocs() == P
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.full(n, 0.5, np.float32))
    story = resilience.degradation_story()
    assert story and story["grow"]["grows"] == 1


def test_serve_requested_cpu_route_is_pinned(tmp_path):
    """Satellite regression: a daemon started with --cpu (requested
    CPU route) is NEVER probed for re-promotion — the grow supervisor
    is a structural no-op, even armed, even degraded."""
    from dr_tpu import serve

    with env_override(DR_TPU_ELASTIC_GROW="1",
                      DR_TPU_ELASTIC_GROW_PROBE_S="0"):
        srv = serve.Server(str(tmp_path / "cp.sock"), batch_window=0.0,
                           cpu=True).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(8, dtype=np.float32)
                faults.inject("serve.flush", "relay_down", times=1)
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                faults.clear()
                st = c.stats()
                assert st["route"] == {"requested": "cpu",
                                       "current": "cpu"}
                # a few more batches: still pinned, never probed
                for _ in range(3):
                    np.testing.assert_allclose(c.scale(x, a=3.0),
                                               x * 3.0, rtol=1e-6)
                st = c.stats()
                assert st["grows"] == 0
                assert st["route"]["current"] == "cpu"
                assert srv._grow_sup is None
        finally:
            faults.clear()
            srv.stop()


def test_serve_repromotion_end_to_end(tmp_path):
    """THE acceptance scenario (SPEC §16.6): a live daemon degraded to
    the CPU route by an injected relay death (DR_TPU_FAULT_SPEC)
    re-claims the device route after the injected fault clears and
    serves the SAME clients bit-equal results — stats()['grows'] == 1,
    route back to 'device', and the 'grow' chapter in the story every
    bench artifact embeds."""
    import time as _t
    from dr_tpu import serve

    with env_override(DR_TPU_ELASTIC_GROW="1",
                      DR_TPU_ELASTIC_GROW_PROBE_S="0.01",
                      DR_TPU_FAULT_SPEC="serve.flush:relay_down"):
        faults.reload_env()
        srv = serve.Server(str(tmp_path / "rp.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(16, dtype=np.float32)
                # batch 1: the injected relay death degrades the claim
                # to the CPU route; the replay answers correctly
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                st = c.stats()
                assert st["route"]["current"] == "cpu"
                assert st["restarts"] == 1 and st["degraded"]
                # the fault has cleared (times=1): the same client's
                # later batches ride the re-promotion, no reconnect
                deadline = _t.monotonic() + 60.0
                while _t.monotonic() < deadline:
                    np.testing.assert_allclose(c.scale(x, a=3.0),
                                               x * 3.0, rtol=1e-6)
                    st = c.stats()
                    if st["grows"]:
                        break
                    _t.sleep(0.02)
                assert st["grows"] == 1, st
                assert st["route"] == {"requested": "device",
                                       "current": "device"}
                assert st["degraded"] is None
                assert c.route()["current"] == "device"
                # still bit-correct after the promotion
                np.testing.assert_allclose(c.scale(x, a=4.0), x * 4.0,
                                           rtol=1e-6)
        finally:
            srv.stop()
            faults.reload_env()
        story = resilience.degradation_story()
        assert story and story["grow"]["grows"] >= 1
        assert "re-promoted" in story["grow"]["reason"]


def test_serve_promotion_grow_fault_stays_on_cpu_route(tmp_path):
    """A fault injected at mesh.grow mid-promotion leaves the session
    SERVING CORRECTLY on the CPU route (classified, absorbed by the
    supervisor, backed off) — grow must never make things worse."""
    from dr_tpu import serve

    with env_override(DR_TPU_ELASTIC_GROW="1",
                      DR_TPU_ELASTIC_GROW_PROBE_S="0"):
        srv = serve.Server(str(tmp_path / "gf.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(8, dtype=np.float32)
                # both armed up front: the relay dies once, and EVERY
                # later promotion attempt dies at the grow boundary
                # (arming after the degrade would race the first
                # zero-delay probe)
                faults.inject("serve.flush", "relay_down", times=1)
                faults.inject("mesh.grow", "transient", times=None)
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                for a in (3.0, 4.0, 5.0):
                    np.testing.assert_allclose(c.scale(x, a=a), x * a,
                                               rtol=1e-6)
                st = c.stats()
                assert st["grows"] == 0
                assert st["route"]["current"] == "cpu"
                assert srv._grow_sup is not None
                assert srv._grow_sup.failures >= 1
                faults.clear()
        finally:
            faults.clear()
            srv.stop()


def test_serve_mesh_growback_between_batches(tmp_path):
    """The shrunken resident claim grows back between batches: a
    device loss mid-batch shrinks the mesh (round 13); with the grow
    hook armed the module supervisor re-admits the returned device a
    few batches later — same clients, bit-equal answers, the grow in
    stats()."""
    import time as _t
    from dr_tpu import serve

    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    with env_override(DR_TPU_ELASTIC="1", DR_TPU_ELASTIC_GROW="1",
                      DR_TPU_ELASTIC_GROW_PROBE_S="0.01"):
        srv = serve.Server(str(tmp_path / "gb.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(16, dtype=np.float32)
                faults.inject("device.lost", "device_lost", times=1)
                np.testing.assert_allclose(c.scale(x, a=3.0), x * 3.0,
                                           rtol=1e-6)
                st = c.stats()
                assert st["shrinks"] == 1
                deadline = _t.monotonic() + 60.0
                while _t.monotonic() < deadline:
                    np.testing.assert_allclose(c.scale(x, a=4.0),
                                               x * 4.0, rtol=1e-6)
                    st = c.stats()
                    if st["grows"]:
                        break
                    _t.sleep(0.02)
                assert st["grows"] == 1, st
                assert st["degraded"] is None
        finally:
            faults.clear()
            srv.stop()
    assert dr_tpu.nprocs() == P
    story = resilience.degradation_story()
    assert story and story["shrink"]["shrinks"] == 1
    assert story["grow"]["grows"] == 1


# ---------------------------------------------------------------------------
# per-tile matrix restore (round 15 satellite): survivors keep live
# values, only dead tiles rewind to the checkpoint
# ---------------------------------------------------------------------------

def test_dense_matrix_restores_per_tile(tmp_path):
    """A checkpointed dense matrix restores PER-TILE (like vectors do
    per-segment): the survivor tile keeps its post-checkpoint write,
    only the dead rank's tile rewinds."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    src = np.arange(4 * P * 3, dtype=np.float32).reshape(4 * P, 3)
    m = dr_tpu.dense_matrix.from_array(src, dr_tpu.row_tiles())
    dr_tpu.checkpoint.save(str(tmp_path / "pt.npz"), m)
    m[0, 0] = 99.0           # rank-0 tile: survivor, must stay live
    m[4 * P - 1, 2] = -77.0  # rank-(P-1) tile: dies, must rewind
    rep = elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=P - 1))
    assert rep.restored == 1 and rep.lost == 0
    assert ("restore", "dense_matrix", "snap") in rep.fates
    expect = src.copy()
    expect[0, 0] = 99.0  # survivor keeps its post-checkpoint write
    np.testing.assert_array_equal(m.materialize(), expect)


def test_sparse_matrix_restores_per_tile(tmp_path):
    """Same per-tile contract for sparse: survivor tiles contribute
    their LIVE triples, dead tiles rewind to the checkpoint's entries
    in their row window."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 2 * P
    rows = np.arange(n)
    cols = np.tile(np.arange(2), P)
    vals = np.arange(n, dtype=np.float32)
    sm = dr_tpu.sparse_matrix.from_coo((n, 4), rows, cols, vals)
    dr_tpu.checkpoint.save(str(tmp_path / "sp.npz"), sm)
    rep = elastic.rescue_session(
        resilience.DeviceLostError("loss", rank=0))
    assert rep.restored == 1 and rep.lost == 0
    assert ("restore", "sparse_matrix", "snap") in rep.fates
    dense = np.zeros((n, 4), np.float32)
    for seg in sm.__dr_segments__():
        r, c, v = seg.triples()
        dense[r, c] = v
    expect = np.zeros((n, 4), np.float32)
    expect[rows, cols] = vals
    np.testing.assert_array_equal(dense, expect)
    # the restored matrix still multiplies correctly
    y = dr_tpu.distributed_vector(n)
    dr_tpu.fill(y, 0.0)
    dr_tpu.gemv(y, sm, np.ones(4, np.float32))
    np.testing.assert_allclose(dr_tpu.to_numpy(y),
                               expect @ np.ones(4, np.float32),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# soak: shrink → grow → shrink vs the never-failed oracle
# ---------------------------------------------------------------------------

def test_fuzz_elastic_shrink_grow_shrink(tmp_path):
    """fuzz_crank.sh grow arm (and the tier-1 slice): random
    kill/revive sequences — checkpoint, kill a rank, revive it, kill
    another — asserting BIT-EQUAL container state vs the never-failed
    oracle at every step, for vectors and a per-tile-restored dense
    matrix, and that the session keeps computing at the end."""
    import jax

    all_devs = jax.devices()
    if len(all_devs) < 2:
        pytest.skip("shrink needs >= 2 devices")
    from dr_tpu.utils import sanitize

    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else max(2, ITERS // 14)
    rng = np.random.default_rng(1900)
    for it in range(iters):
        P = int(rng.integers(2, len(all_devs) + 1))
        dr_tpu.init(all_devs[:P])
        elastic.reset()
        n = int(rng.integers(8, 64))
        oracle = rng.standard_normal(n).astype(np.float32)
        v = dr_tpu.distributed_vector.from_array(oracle)
        msrc = rng.standard_normal((2 * P, 3)).astype(np.float32)
        m = dr_tpu.dense_matrix.from_array(msrc, dr_tpu.row_tiles())
        for step in range(int(rng.integers(2, 5))):
            if sanitize.installed():
                # every kill/revive re-layouts onto a FRESH mesh and
                # legitimately recompiles the same canonical programs
                # (a re-grown mesh is a new Mesh object) — one
                # sanitize epoch per re-layout, the subprocess test's
                # documented pattern, or the soak reads as a
                # recompile storm it is not
                sanitize.reset_epoch()
            cur = dr_tpu.nprocs()
            grown_out = dr_tpu.nprocs() >= len(all_devs)
            if cur > 1 and (grown_out or rng.integers(0, 2)):
                # kill: checkpoint first, so the per-segment/per-tile
                # restore merges to exactly the live (oracle) value
                dr_tpu.checkpoint.save(
                    str(tmp_path / f"s{it}_{step}v.npz"), v)
                dr_tpu.checkpoint.save(
                    str(tmp_path / f"s{it}_{step}m.npz"), m)
                lost = int(rng.integers(0, cur))
                elastic.rescue_session(resilience.DeviceLostError(
                    f"soak kill {it}/{step}", rank=lost))
            else:
                elastic.grow_session(reason=f"soak revive {it}/{step}")
            np.testing.assert_array_equal(dr_tpu.to_numpy(v), oracle,
                                          err_msg=f"it={it} step={step}")
            np.testing.assert_array_equal(m.materialize(), msrc,
                                          err_msg=f"it={it} step={step}")
        got = float(dr_tpu.reduce(v))
        want = float(oracle.astype(np.float64).sum())
        assert abs(got - want) <= 1e-3 * max(1.0, abs(want))


def test_serve_shrink_recorded_even_when_replay_fails(tmp_path):
    """A shrink whose REPLAY then fails still changed the resident
    claim: stats()['shrinks'] and the degraded marker must record it
    (detection lives in the dispatch finally, not the success path)."""
    from dr_tpu import serve

    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    with env_override(DR_TPU_ELASTIC="1"):
        srv = serve.Server(str(tmp_path / "sf.sock"),
                           batch_window=0.0).start()
        try:
            with serve.Client(srv.path, timeout=60.0) as c:
                x = np.arange(8, dtype=np.float32)
                np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                           rtol=1e-6)
                # attempt 1: clean serve.flush visit, then the loss;
                # attempt 2 (the replay): a deterministic fault fails
                # the batch AFTER the shrink already happened
                faults.inject("device.lost", "device_lost", times=1)
                faults.inject("serve.flush", "program", after=1)
                with pytest.raises(resilience.ResilienceError):
                    c.scale(x, a=3.0)
                faults.clear()
                st = c.stats()
                assert st["shrinks"] == 1, st
                assert "shrunken mesh" in (st["degraded"] or ""), st
                # and the daemon keeps serving on the survivors
                np.testing.assert_allclose(c.scale(x, a=4.0), x * 4.0,
                                           rtol=1e-6)
        finally:
            faults.clear()
            srv.stop()


# ------------------------------------------- collective engine (§18)

def test_redistribute_collective_forced_vs_host_bit_identical():
    """The two impls forced via DR_TPU_REDISTRIBUTE must leave the
    IDENTICAL physical padded row — the §18 bit-identity contract the
    fuzz arm cranks, pinned here at one deterministic shape."""
    P = dr_tpu.nprocs()
    n = 4 * P + 3
    src = np.arange(n, dtype=np.float32)
    hops = [None, [n] + [0] * (P - 1),
            [1] * (P - 1) + [n - (P - 1)], None]
    va = dr_tpu.distributed_vector.from_array(src)
    vb = dr_tpu.distributed_vector.from_array(src)
    for d in hops:
        with env_override(DR_TPU_REDISTRIBUTE="collective"):
            dr_tpu.redistribute(va, d)
        with env_override(DR_TPU_REDISTRIBUTE="host"):
            dr_tpu.redistribute(vb, d)
        np.testing.assert_array_equal(np.asarray(va._data),
                                      np.asarray(vb._data))
        np.testing.assert_array_equal(dr_tpu.to_numpy(va), src)


def test_redistribute_exchange_fault_leaves_vector_intact():
    """An injected redistribute.exchange fault surfaces CLASSIFIED
    with the vector exactly as it was — the metadata rebind rolls
    back (§18.2's failure row)."""
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    with faults.injected("redistribute.exchange", "transient",
                         times=1):
        with pytest.raises(resilience.TransientBackendError):
            dr_tpu.redistribute(v, [n] + [0] * (P - 1))
    assert v.distribution is None
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_redistribute_forced_collective_cross_mesh_falls_back():
    """DR_TPU_REDISTRIBUTE=collective on a cross-runtime hop cannot
    run device-side (no shared mesh): the move takes the host-staged
    route ANNOUNCED (warn_fallback), value preserved — never an error,
    never silent."""
    import jax
    from jax.sharding import Mesh
    from dr_tpu.parallel.runtime import Runtime

    devs = jax.devices()
    if len(devs) < 3:
        pytest.skip("needs >= 3 devices")
    small = Runtime(mesh=Mesh(np.asarray(devs[1:3]), ("x",)))
    src = np.arange(10, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    from dr_tpu.utils import fallback
    import warnings
    with env_override(DR_TPU_REDISTRIBUTE="collective",
                      DR_TPU_SILENCE_FALLBACKS=None):
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            dr_tpu.redistribute(v, [4, 6], runtime=small)
    assert v.runtime is small
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    msgs = [str(r.message) for r in rec
            if issubclass(r.category,
                          fallback.MaterializeFallbackWarning)]
    assert any("host-staged" in m for m in msgs), msgs


def test_plan_flush_replay_with_redistribute(tmp_path):
    """Mid-plan-flush device loss with a RECORDED re-layout in the
    queue: the pending redistribute UNDOes its metadata flip (so the
    rescue reads a consistent container), the suffix re-records
    against the shrunken mesh — redistribute included — and the final
    value matches the eager chain (§18.3's elastic-replay contract)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.checkpoint.save(str(tmp_path / "v.npz"), v)
    with env_override(DR_TPU_ELASTIC="1"):
        with faults.injected("device.lost", "device_lost", times=1):
            with dr_tpu.deferred() as p:
                dr_tpu.fill(v, 2.0)
                dr_tpu.redistribute(v, None)
                dr_tpu.for_each(v, _half)
                tot = dr_tpu.reduce(v)
    assert float(tot) == n
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.ones(n, np.float32))
    assert dr_tpu.nprocs() == P - 1
    reasons = [e["reason"] for e in p.log]
    assert "elastic replay" in reasons
