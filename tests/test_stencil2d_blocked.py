"""Temporally-blocked 2-D Pallas stencil (ops/stencil2d_pallas.py,
interpret mode on CPU) vs the XLA double-buffered oracle."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu.algorithms.stencil2d import (stencil2d_iterate,
                                         stencil2d_iterate_blocked)
from dr_tpu.containers.partition import block_cyclic


def _single_tile(src):
    # single-tile partition regardless of mesh size
    return dr_tpu.dense_matrix.from_array(
        src, partition=block_cyclic(grid=(1, 1)))


@pytest.mark.parametrize("steps,tb", [(3, 3), (5, 2), (8, 4)])
def test_blocked_heat_matches_xla(steps, tb):
    m = 32
    src = np.random.default_rng(4).standard_normal(
        (m, 2 * 128)).astype(np.float32)
    w = dr_tpu.heat_step_weights(0.2)
    A = _single_tile(src)
    B = _single_tile(src)
    ref = stencil2d_iterate(A, B, w, steps=steps)
    M = _single_tile(src)
    got = stencil2d_iterate_blocked(M, w, steps, time_block=tb, band=16)
    np.testing.assert_allclose(got.materialize(), ref.materialize(),
                               rtol=2e-4, atol=2e-5)


def test_blocked_full_3x3_weights():
    # all nine taps nonzero (not just the heat cross)
    m = 16
    src = np.linspace(0, 1, m * 128).reshape(m, 128).astype(np.float32)
    w = [[0.05, 0.1, 0.05], [0.1, 0.4, 0.1], [0.05, 0.1, 0.05]]
    A = _single_tile(src)
    B = _single_tile(src)
    ref = stencil2d_iterate(A, B, w, steps=4)
    M = _single_tile(src)
    got = stencil2d_iterate_blocked(M, w, 4, time_block=4, band=8)
    np.testing.assert_allclose(got.materialize(), ref.materialize(),
                               rtol=2e-4, atol=2e-5)


def test_pick_band_accepts_unaligned_divisors():
    from dr_tpu.ops.stencil2d_pallas import SUBLANES, pick_band
    # m with no multiple-of-8 divisor besides none: 12 = 2*2*3
    H = pick_band(12, 128, T=1)
    assert 12 % H == 0
    # aligned divisors are preferred when they exist
    H = pick_band(64, 128, T=1)
    assert H % SUBLANES == 0 and 64 % H == 0
    import pytest
    with pytest.raises(ValueError):
        pick_band(3, 1 << 22, T=1)  # nothing fits a tiny budget


def test_blocked_kernel_consumes_unaligned_band():
    # the kernel itself (not just pick_band) must accept a sublane-
    # unaligned band height: m=24 stepped with band=12
    m = 24
    src = np.random.default_rng(7).standard_normal(
        (m, 128)).astype(np.float32)
    w = dr_tpu.heat_step_weights(0.2)
    A = _single_tile(src)
    B = _single_tile(src)
    ref = stencil2d_iterate(A, B, w, steps=4)
    M = _single_tile(src)
    got = stencil2d_iterate_blocked(M, w, 4, time_block=2, band=12)
    np.testing.assert_allclose(got.materialize(), ref.materialize(),
                               rtol=2e-4, atol=2e-5)


def test_stencil2d_n_matches_iterate_blocked():
    # the fused measurement program applies exactly iters * tb steps
    m, tb, iters = 32, 2, 3
    src = np.random.default_rng(7).standard_normal(
        (m, 128)).astype(np.float32)
    w = dr_tpu.heat_step_weights(0.2)
    from dr_tpu.algorithms.stencil2d import stencil2d_n
    A = _single_tile(src)
    B = _single_tile(src)
    ref = stencil2d_iterate(A, B, w, steps=iters * tb)
    M = _single_tile(src)
    got = stencil2d_n(M, w, iters, time_block=tb)
    np.testing.assert_allclose(got.materialize(), ref.materialize(),
                               rtol=2e-4, atol=2e-5)
