"""2-D heat-equation stencil over tiled dense matrices (BASELINE config 4)."""

import numpy as np
import pytest

import dr_tpu


def _serial_step(u, w):
    w = np.asarray(w, dtype=np.float64)
    rh, rw = w.shape[0] // 2, w.shape[1] // 2
    out = u.copy()
    m, n = u.shape
    acc = np.zeros((m - 2 * rh, n - 2 * rw))
    for di in range(w.shape[0]):
        for dj in range(w.shape[1]):
            acc += w[di, dj] * u[di:m - 2 * rh + di, dj:n - 2 * rw + dj]
    out[rh:m - rh, rw:n - rw] = acc
    return out


def test_heat_single_step():
    m, n = 24, 32
    src = np.random.default_rng(0).standard_normal((m, n))\
        .astype(np.float32)
    w = dr_tpu.heat_step_weights(0.2)
    A = dr_tpu.dense_matrix.from_array(src)
    B = dr_tpu.dense_matrix.from_array(src)
    dr_tpu.stencil2d_transform(A, B, w)
    ref = _serial_step(src.astype(np.float64), w)
    np.testing.assert_allclose(B.materialize(), ref, rtol=1e-4, atol=1e-5)


def test_heat_iterated():
    m, n = 17, 23  # non-divisible shapes exercise the pad mask
    src = np.random.default_rng(1).standard_normal((m, n))\
        .astype(np.float32)
    w = dr_tpu.heat_step_weights(0.25)
    A = dr_tpu.dense_matrix.from_array(src)
    B = dr_tpu.dense_matrix.from_array(src)
    out = dr_tpu.stencil2d_iterate(A, B, w, steps=4)
    ref = src.astype(np.float64)
    for _ in range(4):
        ref = _serial_step(ref, w)
    np.testing.assert_allclose(out.materialize(), ref, rtol=1e-3,
                               atol=1e-5)


def test_heat_iterated_odd_steps():
    """Odd step counts exercise the two-per-iteration loop's remainder
    path (and steps=1 the degenerate zero-iteration case)."""
    m, n = 19, 21
    src = np.random.default_rng(2).standard_normal((m, n))\
        .astype(np.float32)
    w = dr_tpu.heat_step_weights(0.25)
    for steps in (1, 3, 5):
        A = dr_tpu.dense_matrix.from_array(src)
        B = dr_tpu.dense_matrix.from_array(src)
        out = dr_tpu.stencil2d_iterate(A, B, w, steps=steps)
        ref = src.astype(np.float64)
        for _ in range(steps):
            ref = _serial_step(ref, w)
        np.testing.assert_allclose(out.materialize(), ref, rtol=1e-3,
                                   atol=1e-5)


def test_heat_converges_to_mean():
    # physical sanity: with fixed zero boundary, interior decays
    m = n = 16
    src = np.zeros((m, n), dtype=np.float32)
    src[m // 2, n // 2] = 100.0
    w = dr_tpu.heat_step_weights(0.25)
    A = dr_tpu.dense_matrix.from_array(src)
    B = dr_tpu.dense_matrix.from_array(src)
    out = dr_tpu.stencil2d_iterate(A, B, w, steps=20)
    vals = out.materialize()
    assert vals.max() < 100.0
    assert vals.max() > 0.0
    assert np.isfinite(vals).all()


def test_full_3x3_kernel():
    m, n = 12, 12
    src = np.random.default_rng(2).standard_normal((m, n))\
        .astype(np.float32)
    w = np.full((3, 3), 1.0 / 9.0)
    A = dr_tpu.dense_matrix.from_array(src)
    B = dr_tpu.dense_matrix.from_array(src)
    dr_tpu.stencil2d_transform(A, B, w)
    ref = _serial_step(src.astype(np.float64), w)
    np.testing.assert_allclose(B.materialize(), ref, rtol=1e-4, atol=1e-5)
