"""Ring-pipeline schedules + sparse format autoselect (round 9).

The acceptance surface of the spmv overhaul: the pipelined ring
schedule must be BIT-identical to the serial one (same dataflow, same
reduction order — only the ppermute issue order differs), repeated
calls with new b values must hit the program cache (zero recompiles,
stable spmd_guard digest), the format autoselect must route the
adversarial shapes away from the ELL padding blowup, and the
``collectives.ppermute`` fault site must fire classified at the ring
dispatchers with containers untouched.
"""

import os

import numpy as np
import pytest

import dr_tpu
from dr_tpu.algorithms.gemv import SPMV_PHASES, gemv_n, gemv_phases_n
from dr_tpu.utils import faults, resilience
from dr_tpu.utils.env import env_override


def _ring_friendly(m, n, k, seed=0):
    """Random matrix with each row's k entries in k distinct b-blocks:
    ring bucket width 1, always under the blowup gate."""
    P = dr_tpu.nprocs()
    bw = max(1, -(-n // P))
    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(m), k)
    blocks = np.tile(np.arange(k) % P, m)
    cols = np.minimum(blocks * bw + rng.integers(0, bw, m * k), n - 1)
    vals = rng.standard_normal(m * k).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    dense = np.zeros((m, n), np.float32)
    np.add.at(dense, (rows, cols), vals)
    return A, dense


@pytest.fixture
def fmt_env(monkeypatch):
    """Scoped DR_TPU_SPMV_FORMAT / DR_TPU_RING_SCHEDULE control."""
    def set_(fmt=None, sched=None):
        for var, val in (("DR_TPU_SPMV_FORMAT", fmt),
                         ("DR_TPU_RING_SCHEDULE", sched)):
            if val is None:
                monkeypatch.delenv(var, raising=False)
            else:
                monkeypatch.setenv(var, val)
    return set_


def _gemv(A, b, m):
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    dr_tpu.gemv(c, A, b)
    return dr_tpu.to_numpy(c)


def test_ring_gemv_matches_oracle_and_schedules_bitwise(fmt_env):
    """The ring schedule's two issue orders are bit-identical and both
    match the dense oracle (the tentpole's correctness bar)."""
    P = dr_tpu.nprocs()
    m, n, k = 16 * P, 12 * P, min(4, P)
    A, dense = _ring_friendly(m, n, k)
    assert A.ensure_ring(), "test matrix must be ring-eligible"
    b = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    fmt_env(fmt="ring", sched="serial")
    serial = _gemv(A, b, m)
    fmt_env(fmt="ring", sched="pipelined")
    pipelined = _gemv(A, b, m)
    np.testing.assert_array_equal(serial, pipelined)
    np.testing.assert_allclose(serial, dense @ b, rtol=1e-4, atol=1e-5)


def test_ring_gemv_zero_recompiles_new_b(fmt_env):
    """Repeated ring gemv with STREAMING b values reuses one compiled
    program: no cache growth, identical spmd_guard digests."""
    from dr_tpu.utils import sanitize, spmd_guard

    P = dr_tpu.nprocs()
    m, n, k = 8 * P, 8 * P, min(3, P)
    A, dense = _ring_friendly(m, n, k, seed=2)
    assert A.ensure_ring()
    rng = np.random.default_rng(3)
    fmt_env(fmt="ring")
    b0 = rng.standard_normal(n).astype(np.float32)
    got0 = _gemv(A, b0, m)  # compile once
    np.testing.assert_allclose(got0, dense @ b0, rtol=1e-4, atol=1e-5)
    digests = []
    # the sanitizer region replaces the old len(_prog_cache) pin: no
    # tapped cache anywhere may take an insert for a new b value
    with sanitize.zero_recompile("ring gemv with streaming b"):
        for _ in range(3):
            b = rng.standard_normal(n).astype(np.float32)
            with spmd_guard.guard() as g:
                got = _gemv(A, b, m)
            digests.append(g.digest())
            np.testing.assert_allclose(got, dense @ b, rtol=1e-4,
                                       atol=1e-5)
    assert len(set(digests)) == 1, "dispatch digest drifted across calls"


def test_ring_gemv_n_and_phase_truncations(fmt_env):
    """gemv_n's ring arm runs, and every SPMV_PHASES truncation
    compiles and dispatches; the full-program truncation ("combine")
    at iters=1 is exactly the eager ring gemv."""
    P = dr_tpu.nprocs()
    m = 8 * P
    A, dense = _ring_friendly(m, m, min(3, P), seed=4)
    assert A.ensure_ring()
    b = np.ones(m, np.float32)
    bv = dr_tpu.distributed_vector.from_array(b)
    fmt_env(fmt="ring")
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    gemv_n(c, A, bv, 3)
    assert np.isfinite(dr_tpu.to_numpy(c)).all()
    for ph in SPMV_PHASES:
        c = dr_tpu.distributed_vector(m)
        dr_tpu.fill(c, 0.0)
        gemv_phases_n(c, A, bv, ph, 2)
        assert np.isfinite(dr_tpu.to_numpy(c)).all(), ph
    # the last phase IS the full program: iters=1 == eager ring gemv
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    gemv_phases_n(c, A, bv, "combine", 1)
    np.testing.assert_array_equal(dr_tpu.to_numpy(c), _gemv(A, b, m))


def test_ring_gate_rejects_block_skew(fmt_env):
    """A banded-ish matrix whose rows hit ONE b-block pays ~P x bucket
    padding: the ensure_ring gate must refuse (and remember), and the
    ring format request must fall back to a correct path."""
    P = dr_tpu.nprocs()
    if P < 4:
        pytest.skip("needs a wide mesh for the skew to exceed the gate")
    m = 16 * P
    bw = -(-m // P)
    rng = np.random.default_rng(5)
    k = 8
    rows = np.repeat(np.arange(m), k)
    # every entry of a row inside the row's OWN block: one bucket gets
    # all k entries, the other P-1 get zero
    cols = (rows // bw) * bw + rng.integers(0, bw, m * k)
    vals = rng.standard_normal(m * k).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
    assert not A.ensure_ring()
    assert A._ring_state == "no"  # remembered, no rescan
    dense = np.zeros((m, m), np.float32)
    np.add.at(dense, (rows, cols), vals)
    b = rng.standard_normal(m).astype(np.float32)
    fmt_env(fmt="ring")  # must fall back, not fail
    np.testing.assert_allclose(_gemv(A, b, m), dense @ b, rtol=1e-4,
                               atol=1e-4)


def test_2d_ring_combine_matches_psum_and_schedules(fmt_env,
                                                    monkeypatch):
    """The 2-D grid programs' ring combine (all-gather + canonical-
    order sum) agrees with the psum default and is bitwise stable
    across schedules."""
    gp, gq = dr_tpu.factor(dr_tpu.nprocs())
    if gq < 2:
        pytest.skip("needs a 2-D grid with >1 mesh column")
    part = dr_tpu.block_cyclic(grid=(gp, gq))
    rng = np.random.default_rng(6)
    m, n = 40, 36
    d = np.where(rng.random((m, n)) < 0.3,
                 rng.standard_normal((m, n)), 0).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    b = rng.standard_normal(n).astype(np.float32)
    ref = _gemv(A, b, m)  # psum default
    outs = {}
    monkeypatch.setenv("DR_TPU_SPMV_COMBINE", "ring")
    for sched in ("serial", "pipelined"):
        fmt_env(sched=sched)
        outs[sched] = _gemv(A, b, m)
        np.testing.assert_allclose(outs[sched], d @ b, rtol=1e-4,
                                   atol=1e-4)
    np.testing.assert_array_equal(outs["serial"], outs["pipelined"])
    np.testing.assert_allclose(ref, outs["serial"], rtol=1e-5,
                               atol=1e-6)
    # spmm rides the same combine
    B = rng.standard_normal((n, 3)).astype(np.float32)
    got = np.asarray(dr_tpu.spmm(A, B))
    np.testing.assert_allclose(got, d @ B, rtol=1e-4, atol=1e-4)


def test_ring_attention_schedule_ab():
    """The refactored ring attention (shared pipeline helper) produces
    the same output under both schedules — the satellite's no-numeric-
    change bar."""
    import jax.numpy as jnp
    P = dr_tpu.nprocs()
    B, S, h, d = 1, 8 * P, 2, 8
    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.standard_normal((B, S, h, d))
                           .astype(np.float32)) for _ in range(3))
    outs = {}
    with env_override(DR_TPU_RING_SCHEDULE=None):
        for sched in ("serial", "pipelined"):
            os.environ["DR_TPU_RING_SCHEDULE"] = sched
            outs[sched] = np.asarray(
                dr_tpu.ring_attention(q, k, v, causal=True))
    np.testing.assert_allclose(outs["serial"], outs["pipelined"],
                               rtol=1e-6, atol=1e-7)


def test_ppermute_fault_site_classified(fmt_env):
    """An armed collectives.ppermute fault surfaces CLASSIFIED at the
    ring dispatcher with the output container untouched (the dispatch
    never reached the backend)."""
    P = dr_tpu.nprocs()
    m = 8 * P
    A, _ = _ring_friendly(m, m, min(3, P), seed=8)
    assert A.ensure_ring()
    b = np.ones(m, np.float32)
    fmt_env(fmt="ring")
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 1.5)
    before = dr_tpu.to_numpy(c)
    with faults.injected("collectives.ppermute", "transient",
                         times=1) as sp:
        with pytest.raises(resilience.TransientBackendError):
            dr_tpu.gemv(c, A, b)
        assert sp.fired == 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(c), before)
    # disarmed: the same call goes through
    dr_tpu.gemv(c, A, b)
    assert np.isfinite(dr_tpu.to_numpy(c)).all()


# ------------------------------------------------------- format autoselect

def test_autoselect_long_row_adversary_picks_csr():
    """One dense row: the ELL kmax blowup the autoselect exists to
    dodge — format csr, the skew remembered so dispatch never rescans."""
    m, n = 64, 64
    rng = np.random.default_rng(9)
    rows = np.concatenate([np.zeros(n, np.int64),
                           rng.integers(0, m, 8)])
    cols = np.concatenate([np.arange(n), rng.integers(0, n, 8)])
    vals = rng.standard_normal(len(rows)).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    assert A.format == "csr"
    assert A._ell_width == -1  # skew recorded at build
    assert not A.ensure_ell()
    dense = np.zeros((m, n), np.float32)
    np.add.at(dense, (rows, cols), vals)
    b = rng.standard_normal(n).astype(np.float32)
    np.testing.assert_allclose(_gemv(A, b, m), dense @ b, rtol=1e-4,
                               atol=1e-4)


def test_autoselect_skewed_but_block_structured_keeps_bcsr():
    """ELL-skewed matrices that still pass the BCSR gates keep the MXU
    path: one dense row PER SHARD over n=512 blows the ELL kmax gate
    (kmax = 512 against 8-row tiles) but fills the touched (8, 128)
    tiles at 1/8 with uniform block-row skew.  Before the fix the
    autoselect forced csr here and spmm_n (no csr arm) crashed where
    the pre-autoselect code ran BCSR."""
    from dr_tpu.algorithms.gemv import spmm_n
    P = dr_tpu.nprocs()
    m, n = 8 * P, 512
    rows = np.repeat(np.arange(0, m, 8), n)
    cols = np.tile(np.arange(n), P)
    rng = np.random.default_rng(13)
    vals = rng.standard_normal(len(rows)).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    assert A.format == "bcsr"
    assert A.ensure_bcsr()
    assert A._ell_width == -1      # the ELL skew memo still stands
    assert not A.ensure_ell()
    dense = np.zeros((m, n), np.float64)
    np.add.at(dense, (rows, cols), vals.astype(np.float64))
    b = rng.standard_normal(n).astype(np.float32)
    np.testing.assert_allclose(_gemv(A, b, m),
                               dense @ b.astype(np.float64),
                               rtol=1e-3, atol=1e-4)
    B = rng.standard_normal((n, 3)).astype(np.float32)
    spmm_n(A, B, 2)                # the pre-fix AssertionError path
    np.testing.assert_allclose(np.asarray(dr_tpu.spmm(A, B)),
                               dense @ B.astype(np.float64),
                               rtol=1e-3, atol=1e-4)


def test_autoselect_banded_picks_bcsr_random_picks_ell():
    """Block-structured sparsity autoselects the MXU tile layout;
    scattered fine-grained sparsity stays ELL."""
    m = 1024
    half = 16
    ii = np.repeat(np.arange(m), 2 * half + 1)
    jj = ii + np.tile(np.arange(-half, half + 1), m)
    keep = (jj >= 0) & (jj < m)
    rng = np.random.default_rng(10)
    vv = rng.standard_normal(int(keep.sum())).astype(np.float32)
    banded = dr_tpu.sparse_matrix.from_coo((m, m), ii[keep], jj[keep],
                                           vv)
    assert banded.format == "bcsr"
    assert banded.ensure_bcsr()

    k = 4
    rows = np.repeat(np.arange(m), k)
    cols = rng.integers(0, m, m * k)
    vals = rng.standard_normal(m * k).astype(np.float32)
    rand = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
    assert rand.format == "ell"


def test_format_env_override_routes_dispatch(fmt_env):
    """DR_TPU_SPMV_FORMAT forces the layout at dispatch regardless of
    the autoselect, and every forced arm matches the oracle."""
    P = dr_tpu.nprocs()
    m = 16 * P
    A, dense = _ring_friendly(m, m, min(4, P), seed=11)
    b = np.random.default_rng(12).standard_normal(m).astype(np.float32)
    ref = dense @ b
    for fmt in ("csr", "ell", "bcsr", "ring"):
        fmt_env(fmt=fmt)
        np.testing.assert_allclose(_gemv(A, b, m), ref, rtol=1e-4,
                                   atol=1e-4, err_msg=fmt)
    fmt_env()  # cleared: back to the autoselect
    np.testing.assert_allclose(_gemv(A, b, m), ref, rtol=1e-4,
                               atol=1e-4)
