"""drlint + DR_TPU_SANITIZE acceptance (docs/SPEC.md §13).

Each rule fires on its known-bad fixture twin and stays silent on the
clean one; suppressions need a reason; the baseline diffs; the repo
itself is clean under ``--check``; and the runtime sanitizer arms,
counts recompiles, and sweeps a real algorithm chain in a
``DR_TPU_SANITIZE=1`` subprocess.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "drlint_fixtures")

_spec = importlib.util.spec_from_file_location(
    "drlint", os.path.join(REPO, "tools", "drlint.py"))
drlint = importlib.util.module_from_spec(_spec)
sys.modules["drlint"] = drlint    # dataclasses resolve the module here
_spec.loader.exec_module(drlint)


def _scan(*names, relpath=None):
    """Run the Linter over fixture files; ``relpath`` fakes the
    repo-relative path (the package-scoped rules R5/R6 only apply under
    ``dr_tpu/``).  Returns the ACTIVE findings."""
    files = []
    for nm in names:
        path = os.path.join(FIXTURES, nm)
        files.append(drlint.FileInfo(path, relpath or
                                     f"tests/drlint_fixtures/{nm}"))
    lin = drlint.Linter(files, set(drlint.RULES), full_scan=False)
    return [f for f in lin.run() if f.status == "active"]


# ---------------------------------------------------------------------------
# each rule: fires on the bad twin, silent on the clean twin
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule", ["R1", "R2", "R3", "R4"])
def test_rule_fires_on_bad_silent_on_clean(rule):
    low = rule.lower()
    bad = _scan(f"{low}_bad.py")
    assert any(f.rule == rule for f in bad), bad
    assert _scan(f"{low}_clean.py") == []


@pytest.mark.parametrize("rule", ["R5", "R6"])
def test_package_scoped_rules(rule):
    """R5/R6 apply inside dr_tpu/ — scan the twins under a faked
    package relpath."""
    low = rule.lower()
    bad = _scan(f"{low}_bad.py", relpath=f"dr_tpu/_fx_{low}.py")
    assert any(f.rule == rule for f in bad), bad
    assert _scan(f"{low}_clean.py",
                 relpath=f"dr_tpu/_fx_{low}c.py") == []


def test_r5_catches_both_shapes():
    bad = _scan("r5_bad.py", relpath="dr_tpu/_fx_r5.py")
    msgs = " | ".join(f.msg for f in bad)
    assert "warnings.warn" in msgs and "broad except" in msgs


def test_r6_catches_both_shapes():
    bad = _scan("r6_bad.py", relpath="dr_tpu/_fx_r6.py")
    msgs = " | ".join(f.msg for f in bad)
    assert "plain dict" in msgs and "immediately-invoked" in msgs


def test_r9_fires_on_bad_silent_on_clean():
    """All three per-site shapes fire on the bad twin (footprint-less
    _FusedOp, underived reads/writes, record_opaque missing writes);
    the derivation chaser accepts the clean twin's tuple-unpack,
    IfExp, concatenation, genexp, and explicit-barrier forms."""
    bad = [f for f in _scan("r9_bad.py") if f.rule == "R9"]
    msgs = " | ".join(f.msg for f in bad)
    assert "no reads=/writes=" in msgs, bad
    assert "reads= is not derived" in msgs, bad
    assert "writes= is not derived" in msgs, bad
    assert "record_opaque without writes" in msgs, bad
    assert _scan("r9_clean.py") == []


def test_r10_path_scope_fires_on_bad_silent_on_clean():
    """R10 applies under the EFFECTIVE dr_tpu/plan/ relpath — the
    twins opt in via the path-valued scope pragma; direct
    .reads/.writes loads fire, the interference-helper route is
    silent."""
    bad = [f for f in _scan("r10_bad.py") if f.rule == "R10"]
    assert len(bad) == 2, bad
    assert all("plan/interference.py" in f.msg for f in bad)
    assert _scan("r10_clean.py") == []


def test_outside_package_r5_r6_module_rules_do_not_apply(tmp_path):
    """The same snippets under a tests/ relpath — with the fixture's
    scope=package pragma stripped — are NOT findings (the
    immediately-invoked jit check still applies everywhere)."""
    src = open(os.path.join(FIXTURES, "r5_bad.py")).read()
    stripped = "\n".join(ln for ln in src.splitlines()
                         if "drlint: scope=package" not in ln)
    p = tmp_path / "r5_unscoped.py"
    p.write_text(stripped + "\n")
    fi = drlint.FileInfo(str(p), "tests/drlint_fixtures/r5_unscoped.py")
    lin = drlint.Linter([fi], set(drlint.RULES), full_scan=False)
    assert [f for f in lin.run() if f.status == "active"] == []
    active = _scan("r6_bad.py")
    assert all("immediately-invoked" in f.msg for f in active)


def test_scope_pragma_fires_package_rules_from_cli():
    """The acceptance bullet: a direct CLI scan of EVERY bad twin exits
    non-zero — the R5 twins ride the scope=package pragma for it."""
    for nm in sorted(os.listdir(FIXTURES)):
        if nm.endswith("_bad.py"):
            path = os.path.join(FIXTURES, nm)
            assert drlint.main(["--no-baseline", path]) == 1, nm


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_suppression_with_reason_waives():
    """Same-line, line-above, AND stacked line-above waivers all apply
    (the fixture's stacked pair covers two different rules on one
    statement)."""
    assert _scan("suppress_ok.py") == []


def test_r2_membership_test_is_a_read():
    """Review fix: `"DR_TPU_X" in os.environ` is a read R2 must see —
    the clean twin's env_raw(...) is not None form stays silent."""
    bad = _scan("r2_bad.py")
    assert any("membership" in f.msg for f in bad), bad


def test_pending_waiver_does_not_leak_past_inline_form(tmp_path):
    """Review fix: a line-above waiver followed by a line carrying its
    own inline waiver is consumed THERE — it must not fall through and
    suppress an unrelated finding on the next statement."""
    src = (
        "import os\n"
        "# drlint: ok[R2] above-line waiver\n"
        'a = os.environ.get("DR_TPU_SANITIZE")  # drlint: ok[R2] inline\n'
        'b = os.environ.get("DR_TPU_SANITIZE")\n')
    p = tmp_path / "leak.py"
    p.write_text(src)
    fi = drlint.FileInfo(str(p), "tests/drlint_fixtures/leak.py")
    lin = drlint.Linter([fi], set(drlint.RULES), full_scan=False)
    active = [f for f in lin.run() if f.status == "active"]
    assert any(f.rule == "R2" and f.line == 4 for f in active), active
    assert not any(f.line == 3 for f in active), active


def test_reasonless_waiver_cannot_disarm_another_rules_reasoned_one(
        tmp_path):
    """Review fix: reasons are tracked PER RULE — a bare ok[R5] on the
    line above must not eat the reason of a valid inline ok[R2]."""
    src = (
        "import os\n"
        "# drlint: ok[R5]\n"
        'a = os.environ.get("DR_TPU_SANITIZE")  # drlint: ok[R2] fine\n')
    p = tmp_path / "perrule.py"
    p.write_text(src)
    fi = drlint.FileInfo(str(p), "tests/drlint_fixtures/perrule.py")
    lin = drlint.Linter([fi], set(drlint.RULES), full_scan=False)
    active = [f for f in lin.run() if f.status == "active"]
    # the bare waiver is its own R0 finding, but the R2 stays waived
    assert {f.rule for f in active} == {"R0"}, active


def test_unparseable_file_fails_the_gate(tmp_path):
    """Review fix: a SyntaxError must be an ACTIVE finding, not a
    silently skipped file — the CI gate exits non-zero."""
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    assert drlint.main(["--no-baseline", str(p)]) == 1


def test_suppression_without_reason_is_a_finding():
    active = _scan("suppress_bad.py")
    rules = {f.rule for f in active}
    assert "R0" in rules, active          # the bare waiver itself
    assert "R2" in rules, active          # and it does NOT waive


def test_rule_subset_scoping():
    """--rules R4 must not report the R2 fixture."""
    path = os.path.join(FIXTURES, "r2_bad.py")
    fi = drlint.FileInfo(path, "tests/drlint_fixtures/r2_bad.py")
    lin = drlint.Linter([fi], {"R0", "R4"}, full_scan=False)
    assert [f for f in lin.run() if f.status == "active"] == []


# ---------------------------------------------------------------------------
# CLI: exit codes, JSON report, baseline diffing
# ---------------------------------------------------------------------------

def test_cli_exit_codes():
    bad = os.path.join(FIXTURES, "r2_bad.py")
    clean = os.path.join(FIXTURES, "r2_clean.py")
    assert drlint.main(["--no-baseline", bad]) == 1
    assert drlint.main(["--no-baseline", clean]) == 0


def test_json_report(tmp_path):
    bad = os.path.join(FIXTURES, "r4_bad.py")
    out = tmp_path / "report.json"
    assert drlint.main(["--no-baseline", "--json", str(out), bad]) == 1
    report = json.loads(out.read_text())
    assert report["summary"]["active"] >= 1
    assert any(f["rule"] == "R4" and f["status"] == "active"
               for f in report["findings"])


def test_baseline_burn_down(tmp_path):
    """write-baseline accepts the current findings; --check then passes
    until a NEW finding appears; fixing the finding leaves a stale
    entry note, not a failure."""
    base = tmp_path / "baseline.json"
    bad = os.path.join(FIXTURES, "r2_bad.py")
    bad2 = os.path.join(FIXTURES, "r4_bad.py")
    assert drlint.main(["--baseline", str(base),
                        "--write-baseline", bad]) == 0
    recorded = json.loads(base.read_text())["findings"]
    assert recorded and all(v >= 1 for v in recorded.values())
    # same findings: baselined, exit 0
    assert drlint.main(["--baseline", str(base), "--check", bad]) == 0
    # a new file's findings are NOT covered: exit 1
    assert drlint.main(["--baseline", str(base), "--check",
                        bad, bad2]) == 1
    # the finding set shrank: still exit 0 (stale entries just noted)
    clean = os.path.join(FIXTURES, "r2_clean.py")
    assert drlint.main(["--baseline", str(base), "--check", clean]) == 0


def test_repo_is_clean_under_check():
    """The acceptance gate: the default whole-repo scan has zero
    non-baselined findings (and the shipped baseline is empty)."""
    assert drlint.main(["--check"]) == 0
    baseline = os.path.join(REPO, "tools", "drlint_baseline.json")
    if os.path.exists(baseline):
        assert json.loads(open(baseline).read()).get("findings") == {}


# ---------------------------------------------------------------------------
# DR_TPU_SANITIZE runtime half
# ---------------------------------------------------------------------------

def test_zero_recompile_region_catches_insert():
    from dr_tpu.utils import sanitize
    from dr_tpu.utils.spmd_guard import TappedCache
    cache = TappedCache()
    with sanitize.zero_recompile("warm region"):
        cache.get(("k",))                    # lookups are fine
    with pytest.raises(sanitize.SanitizeError, match="zero-recompile"):
        with sanitize.zero_recompile("cold region"):
            cache[("k",)] = "prog"           # an insert is a compile


def test_recompile_storm_detection():
    from dr_tpu.utils import sanitize
    sanitize.reset_epoch()
    try:
        for _ in range(4):                   # same canonical key, 4x
            sanitize._on_compile(("prog", 64, "float32"))
        sanitize.check_recompiles(limit=4)   # at the budget: fine
        with pytest.raises(sanitize.SanitizeError,
                           match="recompile storm"):
            sanitize.check_recompiles(limit=3)
    finally:
        sanitize.reset_epoch()


def test_canon_portability_check():
    from dr_tpu.utils import sanitize
    # a pinned mesh canonicalizes to a placeholder: portable
    sanitize._on_record(("k",), "(halo,ptr,8)")
    with pytest.raises(sanitize.SanitizeError, match="process-local"):
        sanitize._on_record(
            ("k",), "(halo,<Mesh object at 0x7f2a91c04d30>,8)")


def test_blocked_stencil_inner_compiles_are_counted():
    """Review fix: the blocked stencils' two-level caches store jitted
    programs in a plain inner dict the TappedCache insert tap cannot
    see — _blocked_drive must report each inner store through
    spmd_guard.note_compile, and a warm re-drive must stay cold."""
    from dr_tpu.algorithms.stencil import _blocked_drive, _prog_cache
    from dr_tpu.utils import sanitize, spmd_guard

    class _Cont:
        _data = 0.0

    key = ("drlint_noteblk_fixture",)
    try:
        c0 = spmd_guard.compile_count()
        _blocked_drive(_Cont(), key, steps=5, block=2,
                       make_prog=lambda n: (lambda x: x))
        # outer holder insert + inner block=2 + inner rest=1 (and the
        # setdefault miss counts exactly ONCE — no __setitem__ double)
        assert spmd_guard.compile_count() - c0 == 3
        with sanitize.zero_recompile("warm blocked re-drive"):
            _blocked_drive(_Cont(), key, steps=5, block=2,
                           make_prog=lambda n: (lambda x: x))
    finally:
        _prog_cache.pop(key, None)


def test_preexisting_nan_input_is_not_blamed_on_the_flush(monkeypatch):
    """Review fix: the finite sweep must exempt a run whose containers
    ENTERED the flush non-finite (the eager chain would propagate the
    same NaN), while still catching a program that mints non-finite
    values from finite inputs."""
    import numpy as np
    import dr_tpu
    from dr_tpu.utils import sanitize

    monkeypatch.setattr(sanitize, "_installed", True)
    dr_tpu.init()
    n = 8 * dr_tpu.nprocs()

    def _mul(x, c):
        return x * c

    src = np.zeros(n, np.float32)
    src[0] = float("nan")
    a = dr_tpu.distributed_vector.from_array(src)
    b = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred():          # NaN predates the flush: no error
        dr_tpu.transform(a, b, _mul, 2.0)
    assert np.isnan(dr_tpu.to_numpy(b)[0])

    c = dr_tpu.distributed_vector(n, np.float32)
    d = dr_tpu.distributed_vector(n, np.float32)
    with pytest.raises(sanitize.SanitizeError, match="non-finite"):
        with dr_tpu.deferred():      # finite in, inf out: still caught
            dr_tpu.fill(c, 1.0)
            dr_tpu.transform(c, d, _mul, float("inf"))


def test_check_finite():
    import jax.numpy as jnp
    from dr_tpu.utils import sanitize
    sanitize.check_finite(jnp.asarray([1.0, 2.0]), "ok state")
    sanitize.check_finite(jnp.asarray([1, 2]), "ints are exempt")
    with pytest.raises(sanitize.SanitizeError, match="non-finite"):
        sanitize.check_finite(jnp.asarray([1.0, float("nan")]), "bad")


def test_sanitize_smoke_subprocess():
    """DR_TPU_SANITIZE=1 end-to-end: a small deferred algorithm chain
    runs sanitized (armed hooks, finite flush sweep, per-epoch
    recompile check) in its own process."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import dr_tpu
from dr_tpu.utils import sanitize, spmd_guard

assert sanitize.installed(), "DR_TPU_SANITIZE=1 must arm at import"


def _mul(x, c):
    return x * c


dr_tpu.init()
n = 8 * dr_tpu.nprocs()
a = dr_tpu.distributed_vector(n, np.float32)
b = dr_tpu.distributed_vector(n, np.float32)
sanitize.reset_epoch()
with dr_tpu.deferred():
    dr_tpu.fill(a, 2.0)
    dr_tpu.transform(a, b, _mul, 3.0)
    s = dr_tpu.reduce(b)
assert float(s) == 6.0 * n
# re-record with a new scalar: the strict region must stay cold
with sanitize.zero_recompile("re-record"):
    with dr_tpu.deferred():
        dr_tpu.fill(a, 4.0)
        dr_tpu.transform(a, b, _mul, 5.0)
        s2 = dr_tpu.reduce(b)
    assert float(s2) == 20.0 * n
sanitize.check_recompiles()
assert spmd_guard.compile_count() > 0
print("SANITIZED-OK")
"""
    env = dict(os.environ)
    env["DR_TPU_SANITIZE"] = "1"
    env.pop("DR_TPU_FAULT_SPEC", None)
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SANITIZED-OK" in r.stdout


# ---------------------------------------------------------------------------
# R7: plan-optimizer pass registry drift (ISSUE 15, docs/SPEC.md §21.2)
# ---------------------------------------------------------------------------

def test_r7_plan_opt_registry_drift(tmp_path, monkeypatch):
    """Both drift directions fire: a registered pass without a §21.2
    table row, and a table row naming no registered pass; a fuzz file
    that neither sweeps PASS_NAMES nor names every pass fires too."""
    opt = tmp_path / "opt.py"
    opt.write_text('PASSES = (("merge", None), ("mystery", None))\n',
                   encoding="utf-8")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "SPEC.md").write_text(
        "### 21.2 The pass registry\n"
        "| pass | kind | semantics |\n"
        "| `merge` | rewrite | coalesce |\n"
        "| `stale` | rewrite | gone |\n"
        "## 22. next\n", encoding="utf-8")
    fuzz = tmp_path / "fuzz.py"
    fuzz.write_text("def test_fuzz_plan_opt():\n    pass  # merge\n",
                    encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    files = [drlint.FileInfo(str(opt), "dr_tpu/plan/opt.py"),
             drlint.FileInfo(str(fuzz), "tests/test_fuzz.py")]
    lin = drlint.Linter(files, {"R7", "R0"}, full_scan=True)
    msgs = [f.msg for f in lin.run() if f.rule == "R7"]
    text = " ".join(msgs)
    assert "'mystery'" in text          # registered, undocumented
    assert "'stale'" in text            # documented, unregistered
    assert "PASS_NAMES" in text         # fuzz arm misses 'mystery'


def test_r7_silent_when_registry_and_docs_agree(tmp_path, monkeypatch):
    opt = tmp_path / "opt.py"
    opt.write_text('PASSES = (("merge", None),)\n', encoding="utf-8")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "SPEC.md").write_text(
        "### 21.2 The pass registry\n| `merge` | rewrite | x |\n",
        encoding="utf-8")
    fuzz = tmp_path / "fuzz.py"
    fuzz.write_text(
        "from dr_tpu.plan.opt import PASS_NAMES\n"
        "def test_fuzz_plan_opt():\n    pass\n", encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    files = [drlint.FileInfo(str(opt), "dr_tpu/plan/opt.py"),
             drlint.FileInfo(str(fuzz), "tests/test_fuzz.py")]
    lin = drlint.Linter(files, {"R7", "R0"}, full_scan=True)
    assert [f for f in lin.run() if f.rule == "R7"] == []


# ---------------------------------------------------------------------------
# R8: kernel-arm registry drift (docs/SPEC.md §22.1)
# ---------------------------------------------------------------------------

def _write_r8_faults(tmp_path, sites):
    d = tmp_path / "dr_tpu" / "utils"
    d.mkdir(parents=True)
    body = ", ".join(f'"{s}": ("transient",)' for s in sites)
    (d / "faults.py").write_text("SITES = {%s}\n" % body,
                                 encoding="utf-8")


def test_r8_kernel_registry_drift(tmp_path, monkeypatch):
    """Every closure direction fires: an unregistered env override, a
    missing kernel module, a module without supported(), an empty
    fallback declaration, an unregistered fault site, both SPEC §22.1
    drift directions, and a fuzz file that neither sweeps ARM_NAMES
    nor names every arm."""
    kern = tmp_path / "kernels.py"
    kern.write_text(
        'from dr_tpu.utils.env import env_str\n'
        'ARMS = (\n'
        '    ("bitonic", "DR_TPU_BITONIC_IMPL", "bitonic_pallas",\n'
        '     "lax.sort", "kernel.build"),\n'
        '    ("mystery", "DR_TPU_MYSTERY_IMPL", "missing_pallas",\n'
        '     "", "no.such.site"),\n'
        ')\n'
        'env_str("DR_TPU_BITONIC_IMPL")\n', encoding="utf-8")
    probe = tmp_path / "bitonic_pallas.py"
    probe.write_text("def helper():\n    pass\n", encoding="utf-8")
    _write_r8_faults(tmp_path, ["kernel.build"])
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "SPEC.md").write_text(
        "### 22.1 The arm registry\n"
        "| arm | env | kernel | fallback | seams |\n"
        "| `bitonic` | x | x | x | x |\n"
        "| `stale` | x | x | x | x |\n"
        "## 23. next\n", encoding="utf-8")
    fuzz = tmp_path / "fuzz.py"
    fuzz.write_text(
        "def test_fuzz_kernel_parity():\n    pass  # bitonic\n",
        encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    files = [drlint.FileInfo(str(kern), "dr_tpu/ops/kernels.py"),
             drlint.FileInfo(str(probe),
                             "dr_tpu/ops/bitonic_pallas.py"),
             drlint.FileInfo(str(fuzz), "tests/test_fuzz.py")]
    lin = drlint.Linter(files, {"R8", "R0"}, full_scan=True)
    msgs = [f.msg for f in lin.run() if f.rule == "R8"]
    text = " ".join(msgs)
    assert "'DR_TPU_MYSTERY_IMPL'" in text   # override never read
    assert "does not exist" in text          # missing kernel module
    assert "supported()" in text             # probe-less module
    assert "no portable" in text             # empty fallback cell
    assert "'no.such.site'" in text          # unregistered fault site
    assert "'mystery'" in text               # registered, undocumented
    assert "'stale'" in text                 # documented, unregistered
    assert "ARM_NAMES" in text               # fuzz arm misses 'mystery'


def test_r8_silent_when_registry_and_docs_agree(tmp_path, monkeypatch):
    kern = tmp_path / "kernels.py"
    kern.write_text(
        'from dr_tpu.utils.env import env_str\n'
        'ARMS = (\n'
        '    ("bitonic", "DR_TPU_BITONIC_IMPL", "bitonic_pallas",\n'
        '     "lax.sort", "kernel.build"),\n'
        ')\n'
        'env_str("DR_TPU_BITONIC_IMPL")\n', encoding="utf-8")
    probe = tmp_path / "bitonic_pallas.py"
    probe.write_text("def supported():\n    return True\n",
                     encoding="utf-8")
    _write_r8_faults(tmp_path, ["kernel.build"])
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "SPEC.md").write_text(
        "### 22.1 The arm registry\n| `bitonic` | x | x | x | x |\n",
        encoding="utf-8")
    fuzz = tmp_path / "fuzz.py"
    fuzz.write_text(
        "from dr_tpu.ops.kernels import ARM_NAMES\n"
        "def test_fuzz_kernel_parity():\n    pass\n", encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    files = [drlint.FileInfo(str(kern), "dr_tpu/ops/kernels.py"),
             drlint.FileInfo(str(probe),
                             "dr_tpu/ops/bitonic_pallas.py"),
             drlint.FileInfo(str(fuzz), "tests/test_fuzz.py")]
    lin = drlint.Linter(files, {"R8", "R0"}, full_scan=True)
    assert [f for f in lin.run() if f.rule == "R8"] == []


# ---------------------------------------------------------------------------
# R9: plansan footprint-family registry drift (docs/SPEC.md §23.2)
# ---------------------------------------------------------------------------

def test_r9_family_registry_drift(tmp_path, monkeypatch):
    """Every closure direction fires: a family naming a nonexistent
    record method, a record method missing from FAMILIES, an
    undocumented family, a stale §23.2 row, a missing mutation
    battery, a fuzz file without the oracle arm, and an unregistered
    sanitize.verify fault site."""
    ps = tmp_path / "plansan.py"
    ps.write_text(
        'FAMILIES = (\n'
        '    ("generator", "record_fill"),\n'
        '    ("mystery", "record_mystery"),\n'
        ')\n', encoding="utf-8")
    plan = tmp_path / "plan_init.py"
    plan.write_text(
        "class Plan:\n"
        "    def record_fill(self):\n        pass\n"
        "    def record_extra(self):\n        pass\n", encoding="utf-8")
    _write_r8_faults(tmp_path, ["plan.flush"])
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "SPEC.md").write_text(
        "### 23.2 The family table\n"
        "| family | declares | verifier checks |\n"
        "| `generator` | x | x |\n"
        "| `stale` | x | x |\n"
        "## 24. next\n", encoding="utf-8")
    fuzz = tmp_path / "fuzz.py"
    fuzz.write_text("def test_fuzz_plan_opt():\n    pass\n",
                    encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    files = [drlint.FileInfo(str(ps), "dr_tpu/plan/plansan.py"),
             drlint.FileInfo(str(plan), "dr_tpu/plan/__init__.py"),
             drlint.FileInfo(str(fuzz), "tests/test_fuzz.py")]
    lin = drlint.Linter(files, {"R9", "R0"}, full_scan=True)
    msgs = [f.msg for f in lin.run() if f.rule == "R9"]
    text = " ".join(msgs)
    assert "'record_mystery'" in text        # family -> missing method
    assert "'record_extra'" in text          # method -> missing family
    assert "'mystery'" in text and "§23.2" in text   # undocumented
    assert "'stale'" in text                 # documented, unregistered
    assert "test_plansan.py does not exist" in text
    assert "test_fuzz_plansan" in text
    assert "'sanitize.verify'" in text


def test_r9_silent_when_registry_and_docs_agree(tmp_path, monkeypatch):
    ps = tmp_path / "plansan.py"
    ps.write_text('FAMILIES = (("generator", "record_fill"),)\n'
                  'FAMILY_NAMES = tuple(f for f, _m in FAMILIES)\n',
                  encoding="utf-8")
    plan = tmp_path / "plan_init.py"
    plan.write_text("class Plan:\n    def record_fill(self):\n"
                    "        pass\n", encoding="utf-8")
    _write_r8_faults(tmp_path, ["sanitize.verify"])
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "SPEC.md").write_text(
        "### 23.2 The family table\n| `generator` | x | x |\n",
        encoding="utf-8")
    bat = tmp_path / "bat.py"
    bat.write_text("from dr_tpu.plan.plansan import FAMILY_NAMES\n"
                   "def test_families():\n    pass\n", encoding="utf-8")
    fuzz = tmp_path / "fuzz.py"
    fuzz.write_text("def test_fuzz_plansan():\n    pass\n",
                    encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    files = [drlint.FileInfo(str(ps), "dr_tpu/plan/plansan.py"),
             drlint.FileInfo(str(plan), "dr_tpu/plan/__init__.py"),
             drlint.FileInfo(str(bat), "tests/test_plansan.py"),
             drlint.FileInfo(str(fuzz), "tests/test_fuzz.py")]
    lin = drlint.Linter(files, {"R9", "R0"}, full_scan=True)
    assert [f for f in lin.run() if f.rule == "R9"] == []


# ---------------------------------------------------------------------------
# baseline staleness: a dead suppression fails a FULL scan; --prune
# burns it down (partial scans only note — see test_baseline_burn_down)
# ---------------------------------------------------------------------------

def test_stale_baseline_fails_full_scan_and_prunes(tmp_path, monkeypatch):
    pkg = tmp_path / "dr_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text('import warnings\nwarnings.warn("boo")\n',
                   encoding="utf-8")
    (tmp_path / "bench.py").write_text("", encoding="utf-8")
    (tmp_path / "__graft_entry__.py").write_text("", encoding="utf-8")
    monkeypatch.setattr(drlint, "REPO", str(tmp_path))
    base = tmp_path / "base.json"
    args = ["--baseline", str(base), "--rules", "R5"]
    assert drlint.main(args + ["--write-baseline"]) == 0
    assert drlint.main(args + ["--check"]) == 0    # fires, baselined
    mod.write_text("x = 1\n", encoding="utf-8")    # "fix" the finding
    assert drlint.main(args + ["--check"]) == 1    # stale FAILS full scan
    assert drlint.main(args + ["--check", "--prune"]) == 0
    assert json.loads(base.read_text())["findings"] == {}
    assert drlint.main(args + ["--check"]) == 0    # burned down
