"""Resilience layer (utils/resilience + utils/faults): classified
taxonomy, deterministic retry/backoff, deadline watchdog with the
spmd_guard dispatch-trace escalation, fault-spec parsing, injection
sites raising CLASSIFIED errors, and divergence detection under an
injected per-process fault (ISSUE 2 satellite: retry recovers without
re-exec)."""

import io
import time

import numpy as np
import pytest

import dr_tpu
from dr_tpu.utils import fallback, faults, resilience, spmd_guard
from dr_tpu.utils.resilience import (CheckpointCorruptError, DeadlineExpired,
                                     DeviceOOM, ProgramError, RelayDownError,
                                     ResilienceError, TransientBackendError)


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

def test_classify_taxonomy():
    assert resilience.classify("RESOURCE_EXHAUSTED: oom") is DeviceOOM
    assert resilience.classify("Out of memory while...") is DeviceOOM
    assert resilience.classify("relay not listening") is RelayDownError
    assert resilience.classify("ConnectionRefusedError: Connection "
                               "refused") is RelayDownError
    assert resilience.classify("UNAVAILABLE: socket") \
        is TransientBackendError
    assert resilience.classify("device init exceeded 420s (wedged "
                               "tunnel relay?)") is TransientBackendError
    assert resilience.classify("ValueError: bad shape") is ProgramError
    # deterministic errors phrased with "exceeded" must NOT be
    # retryable (no bare "exceeded" transient token)
    assert resilience.classify(
        "RecursionError: maximum recursion depth exceeded") \
        is ProgramError
    # already classified errors keep their class
    assert resilience.classify(DeviceOOM("x")) is DeviceOOM
    # OOM evidence wins even when transient-looking words are present
    assert resilience.classify(
        "UNAVAILABLE: RESOURCE_EXHAUSTED during claim") is DeviceOOM
    # ... but an INCIDENTAL mention of memory is not OOM evidence: a
    # transient transport error must stay retryable
    assert resilience.classify(
        "UNAVAILABLE: transport reset while registering pinned host "
        "memory") is TransientBackendError


def test_classified_wraps_and_passes_through():
    raw = ValueError("UNAVAILABLE: hiccup")
    ce = resilience.classified(raw, site="t")
    assert isinstance(ce, TransientBackendError)
    assert ce.site == "t"
    assert ce.__cause__ is raw
    assert resilience.classified(ce) is ce
    # the subclass hierarchy is part of the contract: corrupt
    # checkpoints are program errors, everything is a ResilienceError
    assert issubclass(CheckpointCorruptError, ProgramError)
    assert all(issubclass(c, ResilienceError) for c in
               (TransientBackendError, RelayDownError, DeviceOOM,
                ProgramError, DeadlineExpired))


# ---------------------------------------------------------------------------
# retry / backoff determinism
# ---------------------------------------------------------------------------

def test_backoff_schedule_deterministic():
    a = resilience.backoff_schedule(6, seed=7)
    b = resilience.backoff_schedule(6, seed=7)
    assert a == b, "seeded jitter must be reproducible"
    c = resilience.backoff_schedule(6, seed=8)
    assert a != c, "different seeds must jitter differently"
    # exponential base shape under the jitter envelope, capped
    base = resilience.backoff_schedule(8, base=1.0, factor=2.0,
                                       max_delay=5.0, jitter=0.25, seed=0)
    for i, d in enumerate(base):
        nominal = min(5.0, 2.0 ** i)
        assert 0.75 * nominal <= d <= 1.25 * nominal


def test_retry_recovers_from_transient_without_reexec():
    """The acceptance-criteria scenario: one injected transient fault,
    retry() recovers IN PROCESS (no re-exec, no new mesh)."""
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(64, dtype=np.float32), halo=hb)
    slept = []
    with faults.injected("halo.exchange", "transient", times=1) as sp:
        resilience.retry(lambda: dr_tpu.halo(dv).exchange(),
                         attempts=3, sleep=slept.append)
        assert sp.fired == 1
    assert len(slept) == 1  # exactly one backoff, then success
    assert slept == resilience.backoff_schedule(2)[:1]
    assert np.isfinite(dr_tpu.to_numpy(dv)).all()


def test_retry_does_not_hammer_nonretryable():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("deterministic bug")

    with pytest.raises(ProgramError):
        resilience.retry(boom, attempts=5, sleep=lambda s: None)
    assert len(calls) == 1, "a classified ProgramError must not retry"

    calls.clear()

    def oom():
        calls.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: 16G")

    with pytest.raises(DeviceOOM):
        resilience.retry(oom, attempts=5, sleep=lambda s: None)
    assert len(calls) == 1, "OOM needs a smaller problem, not a retry"


def test_retry_rejects_nonpositive_attempts():
    with pytest.raises(ValueError, match="attempts"):
        resilience.retry(lambda: 1, attempts=0)


def test_retry_exhaustion_raises_classified():
    calls = []

    def always_transient():
        calls.append(1)
        raise RuntimeError("UNAVAILABLE: still down")

    seen = []
    with pytest.raises(TransientBackendError):
        resilience.retry(always_transient, attempts=3,
                         sleep=lambda s: None,
                         on_retry=lambda i, e, d: seen.append((i, d)))
    assert len(calls) == 3
    assert [i for i, _ in seen] == [0, 1]
    assert [d for _, d in seen] == resilience.backoff_schedule(2)


# ---------------------------------------------------------------------------
# deadline watchdog + dispatch-trace escalation
# ---------------------------------------------------------------------------

def test_with_deadline_passthrough():
    assert resilience.with_deadline(lambda: 42, 5.0) == 42
    with pytest.raises(KeyError):
        resilience.with_deadline(lambda: {}["missing"], 5.0)


def test_deadline_expiry_dumps_dispatch_trace():
    """A hung call under an active guard escalates to the dispatch
    postmortem instead of a silent hang."""
    buf = io.StringIO()
    with spmd_guard.guard() as g:
        dr_tpu.fill(dr_tpu.distributed_vector(64), 1.0)
        assert g.trace, "fill must dispatch"
        with pytest.raises(DeadlineExpired) as ei:
            resilience.with_deadline(lambda: time.sleep(3.0), 0.2,
                                     site="hung_compile", file=buf)
    assert "hung_compile" in str(ei.value)
    out = buf.getvalue()
    assert "recorded dispatches" in out and "[0]" in out
    # without a guard the dump degrades to a pointer, not a crash
    buf2 = io.StringIO()
    assert resilience.dump_dispatch_trace(file=buf2) == 0
    assert "no active spmd_guard" in buf2.getvalue()


# ---------------------------------------------------------------------------
# fault registry: spec grammar, sites, counting
# ---------------------------------------------------------------------------

def test_fault_spec_grammar():
    got = faults.parse_spec(
        "halo.exchange:transient*2;checkpoint.write:truncate@1,"
        "collectives.*:oom*inf@3")
    assert got == [("halo.exchange", "transient", 2, 0),
                   ("checkpoint.write", "truncate", 1, 1),
                   ("collectives.*", "oom", None, 3)]
    with pytest.raises(ValueError):
        faults.parse_spec("no-colon-entry")


def test_fault_count_env_arms_counting_without_spec(monkeypatch):
    """DR_TPU_FAULT_COUNT=1 must arm visit counting even with NO
    injection spec — the coverage-collection mode the docstring
    promises."""
    monkeypatch.delenv("DR_TPU_FAULT_SPEC", raising=False)
    monkeypatch.setenv("DR_TPU_FAULT_COUNT", "1")
    assert faults.reload_env() == 0
    faults.fire("fallback.warn")
    assert faults.stats().get("fallback.warn") == 1
    # leave the registry env-clean for the autouse reload_env teardown
    monkeypatch.delenv("DR_TPU_FAULT_COUNT")
    faults.reload_env()


def test_reload_env_installs_and_warns(monkeypatch):
    monkeypatch.setenv("DR_TPU_FAULT_SPEC",
                       "halo.exchange:transient;bogus.site:transient")
    with pytest.warns(UserWarning, match="matches no registered"):
        assert faults.reload_env() == 1
    assert any("halo.exchange" in p for p in faults.pending())
    monkeypatch.setenv("DR_TPU_FAULT_SPEC", "")
    assert faults.reload_env() == 0
    assert not faults.pending()


def test_unknown_site_and_kind_rejected():
    with pytest.raises(ValueError, match="matches no registered"):
        # drlint: ok[R3] negative test: an unregistered site must be rejected loudly at arm time
        faults.inject("not.a.site", "transient")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.inject("halo.exchange", "lightning")
    # a kind NO matched site supports must error at arm time, not read
    # as a clean sweep (truncate is checkpoint.write-only; fallback.warn
    # is counting-only)
    with pytest.raises(ValueError, match="unsupported"):
        faults.inject("halo.exchange", "truncate")
    with pytest.raises(ValueError, match="unsupported"):
        faults.inject("fallback.warn", "program")


def test_glob_injection_fires_only_where_supported():
    """'*:oom' must fault oom-supporting sites and pass clean through
    the rest (runtime.probe declares no oom kind) without consuming."""
    from dr_tpu.parallel.runtime import probe_devices
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(32, dtype=np.float32), halo=hb)
    with faults.injected("*", "oom", times=1) as sp:
        devs, err = probe_devices(5.0)  # unsupported site: clean pass
        assert err is None and sp.fired == 0
        with pytest.raises(DeviceOOM):
            dr_tpu.halo(dv).exchange()
        assert sp.fired == 1


def test_retry_preserves_cause_chain():
    """Re-raising an ALREADY classified error must keep its __cause__
    (the root-cause traceback the taxonomy exists to preserve)."""
    root = ValueError("root cause")

    def boom():
        raise ProgramError("classified") from root

    with pytest.raises(ProgramError) as ei:
        resilience.retry(boom, attempts=3, sleep=lambda s: None)
    assert ei.value.__cause__ is root
    # newly wrapped errors chain to the raw original
    with pytest.raises(ProgramError) as ei2:
        resilience.retry(lambda: (_ for _ in ()).throw(
            KeyError("raw")), attempts=1, sleep=lambda s: None)
    assert isinstance(ei2.value.__cause__, KeyError)


def test_injection_counting_and_skip():
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(32, dtype=np.float32), halo=hb)
    h = dr_tpu.halo(dv)
    # after=1: first visit passes clean, second faults
    with faults.injected("halo.exchange", "transient", times=1,
                         after=1) as sp:
        h.exchange()
        assert sp.fired == 0
        with pytest.raises(TransientBackendError):
            h.exchange()
        assert sp.fired == 1
        h.exchange()  # exhausted -> clean again
        assert sp.fired == 1
    assert faults.stats().get("halo.exchange", 0) >= 3


def test_injected_sites_raise_classified():
    from dr_tpu.parallel.runtime import probe_devices
    # runtime.probe folds injected faults into its (None, err) contract
    with faults.injected("runtime.probe", "relay_down"):
        devs, err = probe_devices(5.0)
        assert devs is None and "relay not listening" in err
        assert resilience.classify(err) is RelayDownError
    # runtime.init raises directly
    with faults.injected("runtime.init", "transient"):
        with pytest.raises(TransientBackendError):
            dr_tpu.init()
    dr_tpu.init()
    # collectives
    comm = dr_tpu.default_comm()
    data = comm.scatter(np.zeros((dr_tpu.nprocs(), 4), np.float32))
    with faults.injected("collectives.shift", "oom"):
        with pytest.raises(DeviceOOM):
            comm.shift_forward(data, periodic=True)
    # halo reduce
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(32, dtype=np.float32), halo=hb)
    with faults.injected("halo.reduce", "program"):
        with pytest.raises(ProgramError):
            dr_tpu.halo(dv).reduce_plus()


def test_warn_fallback_routed_and_resettable(monkeypatch):
    """Satellite: fallback sites go through the registry (countable by
    the chaos arm) and reset() re-opens the once-per-site budget."""
    monkeypatch.delenv("DR_TPU_SILENCE_FALLBACKS", raising=False)
    fallback.reset()
    faults.arm_counting()
    with pytest.warns(fallback.MaterializeFallbackWarning):
        fallback.warn_fallback("test_op", "test_reason")
    # once per site: the repeat is silent but still COUNTED
    fallback.warn_fallback("test_op", "test_reason")
    assert faults.stats().get("fallback.warn", 0) == 2
    # reset() clears the _seen memory: the site warns again
    fallback.reset()
    with pytest.warns(fallback.MaterializeFallbackWarning):
        fallback.warn_fallback("test_op", "test_reason")


# ---------------------------------------------------------------------------
# spmd_guard divergence under an injected per-process fault
# ---------------------------------------------------------------------------

def test_divergence_under_injected_per_process_fault():
    """A fault that eats ONE process's dispatch (here: simulated by a
    single-shot dispatch.cache injection in the second 'process') must
    surface as a locatable trace divergence — the deadlock class the
    guard exists to catch."""
    def tail(n):
        out = dr_tpu.distributed_vector(n)
        dr_tpu.inclusive_scan(dr_tpu.distributed_vector.from_array(
            np.arange(n, dtype=np.float32)), out)

    n = 128
    with spmd_guard.guard() as ga:  # healthy process
        a = dr_tpu.distributed_vector(n)
        dr_tpu.iota(a, 0)
        dr_tpu.fill(a, 1.0)
        tail(n)
    with spmd_guard.guard() as gb:  # faulted process: fill's dispatch lost
        b = dr_tpu.distributed_vector(n)
        dr_tpu.iota(b, 0)
        with faults.injected("dispatch.cache", "transient", times=1):
            with pytest.raises(TransientBackendError):
                dr_tpu.fill(b, 1.0)
        tail(n)
    assert ga.digest() != gb.digest()
    div = spmd_guard.first_divergence(ga.trace, gb.trace)
    assert div is not None
    i, mine, theirs = div
    assert mine is None or mine != theirs
    # identical traces still report None (the helper verify() now uses)
    assert spmd_guard.first_divergence(ga.trace, list(ga.trace)) is None


# ---------------------------------------------------------------------------
# degradation router pieces
# ---------------------------------------------------------------------------

def test_route_first_touch_decisions():
    ok = resilience.route_first_touch(
        1.0, probe=lambda t: (["dev"], None), is_dead=lambda: False,
        listening=lambda: True)
    assert ok.decision == "ok" and ok.devices == ["dev"]
    dead = resilience.route_first_touch(
        1.0, probe=lambda t: (["dev"], None), is_dead=lambda: True)
    assert dead.decision == "cpu" and dead.probe_skipped
    retry = resilience.route_first_touch(
        1.0, probe=lambda t: (None, "UNAVAILABLE: x"),
        is_dead=lambda: False, listening=lambda: True)
    assert retry.decision == "retry" and "UNAVAILABLE" in retry.err
    # already retried -> degrade, never loop
    cpu = resilience.route_first_touch(
        1.0, retried=True, probe=lambda t: (None, "UNAVAILABLE: x"),
        is_dead=lambda: True, listening=lambda: True)
    assert cpu.decision == "cpu" and not cpu.probe_skipped


def test_degradation_story_assembly():
    env = {"_DR_TPU_BENCH_DEGRADED": "retry failed: boom",
           "_DR_TPU_BENCH_FIRST_ERR": "UNAVAILABLE: first",
           "_DR_TPU_BENCH_RETRIES": "1",
           "_DR_TPU_BENCH_PROBE_S": "12.5"}
    story = resilience.degradation_story(env)
    assert story == {"reason": "retry failed: boom",
                     "first_error": "UNAVAILABLE: first",
                     "retries": 1, "probe_wall_s": 12.5}
    assert resilience.degradation_story({}) is None


def test_degradation_story_serve_markers():
    """Round 11: served runs publish _DR_TPU_SERVE_* markers; the
    story grows a `serve` chapter (queue depth, shed count, restarts)
    so detail.degraded tells the full serving story."""
    serve_env = {"_DR_TPU_SERVE_DEGRADED":
                 "serve: relay died; restarted on the CPU route",
                 "_DR_TPU_SERVE_QUEUE_DEPTH": "7",
                 "_DR_TPU_SERVE_SHED": "2",
                 "_DR_TPU_SERVE_RESTARTS": "1"}
    story = resilience.degradation_story(serve_env)
    assert story["reason"].startswith("serve: relay died")
    assert story["serve"] == {"reason": serve_env["_DR_TPU_SERVE_DEGRADED"],
                              "queue_depth": 7, "shed": 2, "restarts": 1}
    # counters WITHOUT a degradation reason are not a degraded run
    assert resilience.degradation_story(
        {"_DR_TPU_SERVE_QUEUE_DEPTH": "3"}) is None
    # a first-touch degradation keeps its own reason; the serve
    # chapter rides alongside
    both = dict(serve_env, _DR_TPU_BENCH_DEGRADED="relay not listening")
    s2 = resilience.degradation_story(both)
    assert s2["reason"] == "relay not listening"
    assert s2["serve"]["shed"] == 2
