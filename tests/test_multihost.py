"""Multi-process SPMD execution — the MHP/DCN dimension.

The reference tests its MPI backend under mpiexec at several rank counts
(test/gtest/mhp/CMakeLists.txt:27-33); here two OS processes join a
jax.distributed coordinator and run the same collective program over the
global mesh.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).resolve().parent / "multihost_worker.py"

# Some jaxlib builds ship a CPU backend without multiprocess SPMD at
# all ("Multiprocess computations aren't implemented on the CPU
# backend") — a toolchain capability, not a code property.  Memoized
# so the sweep pays the discovery cost once, not per rank count.
_BACKEND_CANT = "Multiprocess computations aren't implemented"
_env_skip = [False]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2, 3, 4])
def test_multi_process_spmd(nproc):
    """2-, 3- and 4-process SPMD (the reference's 1-4-rank mpiexec
    sweep, test/gtest/mhp/CMakeLists.txt:27-33; 1 rank = the regular
    suite).  3 processes exercises uneven tails everywhere; at 4,
    factor(4) is a (2, 2) grid, so the 2-D sparse-gemv branch in the
    worker runs across a process boundary."""
    if _env_skip[0]:
        pytest.skip("jaxlib CPU backend lacks multiprocess SPMD")
    port = _free_port()
    env = dict(os.environ)
    env["XLA_FLAGS"] = ""  # one local device per process
    repo = str(WORKER.parent.parent)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), str(nproc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=WORKER.parent.parent)
        for pid in range(nproc)
    ]
    import threading

    outs = [None] * nproc

    def drain(i, p):
        outs[i], _ = p.communicate()

    threads = [threading.Thread(target=drain, args=(i, p))
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    # poll instead of a blind join: a worker dying EARLY (backend
    # rejects multiprocess, import error) would otherwise leave its
    # peers blocked in collectives until the full deadline — the
    # failure is already decided the moment any worker exits nonzero
    import time
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            break
        if any(p.poll() not in (None, 0) for p in procs):
            time.sleep(2)  # let peers fail/flush on their own first
            break
        time.sleep(0.5)
    # a dead worker leaves its peer blocked in a collective: kill
    # stragglers so every worker's own output is still reported
    for p in procs:
        if p.poll() is None:
            p.kill()
    for t in threads:
        t.join(timeout=30)
    blob = "".join(o or "" for o in outs)
    if _BACKEND_CANT in blob:
        _env_skip[0] = True
        pytest.skip("jaxlib CPU backend lacks multiprocess SPMD "
                    "(toolchain capability, not a code property)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"proc {pid} failed:\n{(out or '')[-2000:]}"
        assert "MULTIHOST-OK" in (out or "")
