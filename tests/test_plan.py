"""Deferred execution plans (ISSUE 3, dr_tpu/plan.py).

Acceptance pins: a recorded 8-op chain (fill -> for_each -> exchange ->
transform -> reduce ...) executes in <= 2 tap dispatches, BIT-identical
to the eager sequence; re-recording with new scalar values compiles
ZERO new programs and keeps the spmd_guard dispatch digest stable.
"""

import warnings

import numpy as np
import pytest

import jax

import dr_tpu
from dr_tpu import plan as dr_plan
from dr_tpu import views
from dr_tpu.utils import fallback, faults, resilience, sanitize, spmd_guard


# module-level ops: program-cache keys pin callable identity, so tests
# must not mint fresh lambdas per call
def _scale(x, c):
    return x * c


def _shift(x, c):
    return x + c


def _mul2(x, y):
    return x * y


def _swap_sum(x, y):
    return (x + y, x - y)


def _pair(n, hb=None, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    src = rng.standard_normal(n).astype(dtype)
    return (dr_tpu.distributed_vector.from_array(src, halo=hb),
            dr_tpu.distributed_vector.from_array(src, halo=hb))


def test_deferred_8op_chain_dispatches_and_bit_identity():
    """The ISSUE 3 acceptance chain: <= 2 dispatches, bit-identical."""
    P = dr_tpu.nprocs()
    n = 24 * P
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    ea, da = _pair(n, hb, seed=1)
    eb, db = _pair(n, hb, seed=2)

    def chain(a, b):
        dr_tpu.fill(a, 0.25)                             # 1
        dr_tpu.iota(b, 3)                                # 2
        dr_tpu.for_each(a, _scale, 1.5)                  # 3
        dr_tpu.halo(a).exchange()                        # 4
        dr_tpu.transform(views.zip(a, b), b, _mul2)      # 5
        dr_tpu.for_each(b, _shift, 2.0)                  # 6
        dr_tpu.halo(a).reduce_plus()                     # 7
        return dr_tpu.reduce(b)                          # 8

    want = chain(ea, eb)
    d0 = spmd_guard.dispatch_count()
    with dr_tpu.deferred() as p:
        got = chain(da, db)
    used = spmd_guard.dispatch_count() - d0
    assert used <= 2, p.explain()
    assert isinstance(got, dr_plan.PlanScalar)
    assert float(got) == want
    np.testing.assert_array_equal(dr_tpu.to_numpy(da), dr_tpu.to_numpy(ea))
    np.testing.assert_array_equal(dr_tpu.to_numpy(db), dr_tpu.to_numpy(eb))
    st = p.stats()
    assert st["fused_runs"] == 1 and st["fused_ops"] == 8
    assert st["dispatches"] == used


def test_zero_recompile_and_stable_digest():
    """Re-recording with new fill values / op coefficients must hit the
    compiled program: zero new cache entries, identical guard digest."""
    P = dr_tpu.nprocs()
    n = 16 * P
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    a = dr_tpu.distributed_vector(n, np.float32, halo=hb)
    b = dr_tpu.distributed_vector(n, np.float32, halo=hb)

    def region(fv, cv):
        with dr_tpu.deferred():
            dr_tpu.fill(a, fv)
            dr_tpu.for_each(a, _scale, cv)
            dr_tpu.halo(a).exchange()
            dr_tpu.transform(a, b, _shift, cv)
            s = dr_tpu.reduce(b)
        return float(s)

    v1 = region(2.0, 1.5)
    # zero-recompile contract via the sanitizer region (SPEC §13.4):
    # stricter than the old per-cache len() pins — NO tapped cache in
    # the package may take an insert while re-recording
    with sanitize.zero_recompile("plan re-record with new values"), \
            spmd_guard.guard() as g1:
        v2 = region(3.0, 2.5)
    with sanitize.zero_recompile("plan re-record, third pass"), \
            spmd_guard.guard() as g2:
        v3 = region(-1.0, 0.5)
    assert g1.digest() == g2.digest(), "dispatch digest drifted"
    # the values must still respond to the scalars (not baked in)
    assert v1 == n * (2.0 * 1.5 + 1.5)
    assert v2 == n * (3.0 * 2.5 + 2.5)
    assert v3 == n * 0.0


def test_reduction_rides_the_carry():
    """A mid-chain reduce feeds a later op in the SAME region without
    leaving the device: still exactly one dispatch."""
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    d0 = spmd_guard.dispatch_count()
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 2.0)
        tot = dr_tpu.reduce(a)           # 2n, pending
        dr_tpu.fill(b, tot)              # in-program scalar ref
        tot2 = dr_tpu.reduce(b)
    assert spmd_guard.dispatch_count() - d0 == 1, p.explain()
    assert float(tot) == 2.0 * n
    assert float(tot2) == 2.0 * n * n


def test_posted_scalar_feeding_later_op_keeps_init_fold():
    """reduce(r, init=...) carries a HOST-side fold: consuming the
    handle in a later recorded op must apply it (the producer run
    splits off and the consumer reads the posted value), not drop it
    for the raw in-program carry."""
    P = dr_tpu.nprocs()
    n = 8 * P
    ea = dr_tpu.distributed_vector(n, np.float32)
    eb = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(ea, 2.0)
    es = dr_tpu.reduce(ea, 10.0)
    dr_tpu.fill(eb, es)
    want = dr_tpu.to_numpy(eb)

    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 2.0)
        s = dr_tpu.reduce(a, 10.0)
        dr_tpu.fill(b, s)
    np.testing.assert_array_equal(dr_tpu.to_numpy(b), want)
    assert float(s) == 10.0 + 2.0 * n
    assert p.stats()["fused_runs"] == 2  # producer/consumer split
    # the raw-device accessor refuses posted handles instead of lying
    with pytest.raises(ValueError):
        s.device()


def test_plan_scalar_equality_resolves():
    """`reduce(a) == expected` inside a region must resolve (flush)
    rather than silently compare object identity."""
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred():
        dr_tpu.fill(a, 2.0)
        s = dr_tpu.reduce(a)
        assert s == 2.0 * n
        assert s != 2.0 * n + 1
        assert s == dr_tpu.reduce(a)  # PlanScalar vs PlanScalar
    with pytest.raises(TypeError):
        hash(s)  # hashing would be a hidden flush: loudly unhashable


def test_scalar_read_flushes_mid_region():
    """Resolving a PlanScalar inside the region is a host-materialization
    flush point; recording continues afterwards in a fresh run."""
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 3.0)
        tot = dr_tpu.reduce(a)
        assert float(tot) == 3.0 * n      # forces a flush
        dr_tpu.for_each(a, _shift, 1.0)   # records into a second run
    assert dr_tpu.to_numpy(a)[0] == 4.0
    assert p.stats()["flushes"] == 2
    assert "scalar read" in p.explain()


def test_reduce_init_and_transform_reduce_deferred():
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    ea = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(ea, 2.0)
    want = dr_tpu.reduce(ea, 10.0)
    want_tr = dr_tpu.transform_reduce(ea, transform_op=_scale,
                                      transform_args=(3.0,))
    want_dot = dr_tpu.dot(ea, ea, init=1.0)
    with dr_tpu.deferred():
        dr_tpu.fill(a, 2.0)
        got = dr_tpu.reduce(a, 10.0)
        got_tr = dr_tpu.transform_reduce(a, transform_op=_scale,
                                         transform_args=(3.0,))
        got_dot = dr_tpu.dot(a, a, init=1.0)
    assert float(got) == want
    assert float(got_tr) == want_tr
    assert float(got_dot) == want_dot


def test_host_materialization_flushes():
    """to_numpy / indexing / get() inside the region observe the
    recorded writes (the container hooks flush first)."""
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 7.0)
        assert a[0] == 7.0               # __getitem__ flush
        dr_tpu.for_each(a, _shift, 1.0)
        np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                      np.full(n, 8.0, np.float32))
    assert p.stats()["flushes"] >= 2


def test_nonfusible_sort_flushes_and_warns(monkeypatch):
    """sort inside a region forces a flush, announced through the
    fallback registry (warn_fallback("plan", ...)) — and the recorded
    prefix lands BEFORE the sort, preserving program order."""
    monkeypatch.delenv("DR_TPU_SILENCE_FALLBACKS", raising=False)
    fallback.reset()
    P = dr_tpu.nprocs()
    n = 8 * P
    rng = np.random.default_rng(5)
    src = rng.standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with dr_tpu.deferred() as p:
            dr_tpu.for_each(a, _scale, -1.0)
            dr_tpu.sort(a)
    hits = [x for x in w
            if issubclass(x.category, fallback.MaterializeFallbackWarning)
            and "dr_tpu.plan" in str(x.message)]
    assert hits, [str(x.message) for x in w]
    np.testing.assert_array_equal(dr_tpu.to_numpy(a), np.sort(-src))
    assert any("non-fusible" in e["reason"] for e in p.log)


def test_gemv_records_opaque_keeps_runs(monkeypatch):
    """Round 9: gemv inside a region records as an ordered OPAQUE op
    (like inclusive_scan) — the surrounding fusible runs stay fused, no
    warn_fallback("plan", ...) cliff, record order preserved, results
    identical to the eager sequence."""
    monkeypatch.delenv("DR_TPU_SILENCE_FALLBACKS", raising=False)
    fallback.reset()
    P = dr_tpu.nprocs()
    m = 8 * P
    rng = np.random.default_rng(11)
    d = np.where(rng.random((m, m)) < 0.3,
                 rng.standard_normal((m, m)), 0).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo(
        (m, m), *np.nonzero(d), d[np.nonzero(d)])
    bsrc = rng.standard_normal(m).astype(np.float32)

    def chain(c, b):
        dr_tpu.fill(c, 0.25)
        dr_tpu.for_each(b, _scale, 2.0)
        dr_tpu.gemv(c, A, b)
        dr_tpu.for_each(c, _shift, 1.0)
        return dr_tpu.reduce(c)

    ec = dr_tpu.distributed_vector(m)
    eb = dr_tpu.distributed_vector.from_array(bsrc)
    want = chain(ec, eb)

    dc = dr_tpu.distributed_vector(m)
    db = dr_tpu.distributed_vector.from_array(bsrc)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with dr_tpu.deferred() as p:
            got = chain(dc, db)
    hits = [x for x in w
            if issubclass(x.category, fallback.MaterializeFallbackWarning)
            and "dr_tpu.plan" in str(x.message)]
    assert not hits, [str(x.message) for x in hits]
    assert float(got) == want
    np.testing.assert_array_equal(dr_tpu.to_numpy(dc),
                                  dr_tpu.to_numpy(ec))
    st = p.stats()
    assert st["opaque_ops"] == 1, st
    assert st["fused_runs"] == 2, st  # runs SURVIVE around the gemv
    assert not any("non-fusible" in e["reason"] for e in p.log)


def test_opaque_scan_keeps_order():
    P = dr_tpu.nprocs()
    n = 16 * P
    src = np.arange(n, dtype=np.float32)
    e_in, d_in = _pair(n, seed=3)
    e_out = dr_tpu.distributed_vector(n, np.float32)
    d_out = dr_tpu.distributed_vector(n, np.float32)
    e_in.assign_array(src)
    d_in.assign_array(src)

    dr_tpu.fill(e_in, 1.0)
    dr_tpu.inclusive_scan(e_in, e_out)
    dr_tpu.for_each(e_out, _scale, 2.0)
    want = dr_tpu.to_numpy(e_out)

    with dr_tpu.deferred() as p:
        dr_tpu.fill(d_in, 1.0)
        dr_tpu.inclusive_scan(d_in, d_out)
        dr_tpu.for_each(d_out, _scale, 2.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(d_out), want)
    st = p.stats()
    assert st["opaque_ops"] == 1 and st["fused_runs"] == 2


def test_deferred_zip_foreach_and_subranges():
    P = dr_tpu.nprocs()
    n = 24 * P
    ea, da = _pair(n, seed=7)
    eb, db = _pair(n, seed=8)

    def chain(a, b):
        dr_tpu.for_each(views.zip(a, b), _swap_sum)
        dr_tpu.fill(a[2:n - 3], -1.0)
        dr_tpu.transform(a[1:n - 1], b[1:n - 1], _shift, 0.5)
        return dr_tpu.reduce(b[3:n], op=max)

    want = chain(ea, eb)
    with dr_tpu.deferred():
        got = chain(da, db)
    assert float(got) == want
    np.testing.assert_array_equal(dr_tpu.to_numpy(da), dr_tpu.to_numpy(ea))
    np.testing.assert_array_equal(dr_tpu.to_numpy(db), dr_tpu.to_numpy(eb))


def test_deferred_host_copy_splice():
    P = dr_tpu.nprocs()
    n = 16 * P
    src = np.linspace(-1, 1, n).astype(np.float32)
    ea, da = _pair(n, seed=9)
    dr_tpu.copy(src, ea)
    dr_tpu.for_each(ea, _scale, 2.0)
    with dr_tpu.deferred():
        dr_tpu.copy(src, da)
        dr_tpu.for_each(da, _scale, 2.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(da), dr_tpu.to_numpy(ea))


def test_fused_loop_helpers_flush_pending_writes():
    """The bench *_n fused loops read container buffers directly; a
    deferred region's pending writes must land first (review finding:
    dot_n on a just-recorded fill returned the stale zeros)."""
    from dr_tpu.algorithms.reduce import dot_n
    from dr_tpu.algorithms.scan import inclusive_scan_n
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    s = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred():
        dr_tpu.fill(a, 2.0)
        dr_tpu.fill(b, 1.0)
        assert float(dot_n(a, b, 1)) == 2.0 * n
        dr_tpu.fill(a, 3.0)
        inclusive_scan_n(a, s, 1)
    np.testing.assert_allclose(dr_tpu.to_numpy(s),
                               np.cumsum(np.full(n, 3.0, np.float32)),
                               rtol=1e-6)


def test_deferred_mismatched_copy_raises_like_eager():
    """A wrong-length host copy raises eagerly (_write_window's shape
    check); the recorded splice must reject it too, not silently write
    a clipped prefix plus garbage."""
    P = dr_tpu.nprocs()
    n = 16 * P
    d = dr_tpu.distributed_vector(n, np.float32)
    src = np.arange(n // 2, dtype=np.float32)
    with pytest.raises(Exception):
        dr_tpu.copy(src, d)  # eager raises
    with pytest.raises(ValueError):
        with dr_tpu.deferred():
            dr_tpu.copy(src, d)  # recorded path must raise too


def test_deferred_stencil_transform_bit_identical():
    P = dr_tpu.nprocs()
    n = 32 * P
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    w = [0.25, 0.5, 0.25]
    ea, da = _pair(n, hb, seed=11)
    eb, db = _pair(n, hb, seed=12)

    def chain(a, b):
        dr_tpu.halo(a).exchange()
        dr_tpu.stencil_transform(a, b, w)
        return dr_tpu.reduce(b)

    want = chain(ea, eb)
    d0 = spmd_guard.dispatch_count()
    with dr_tpu.deferred():
        got = chain(da, db)
    assert spmd_guard.dispatch_count() - d0 == 1
    assert float(got) == want
    np.testing.assert_array_equal(dr_tpu.to_numpy(db), dr_tpu.to_numpy(eb))


def test_faulted_flush_is_clean():
    """A classified fault at the flush boundary: the region raises the
    classified error, containers keep their pre-region values, pending
    scalars break loudly, and the plan stays usable afterwards."""
    P = dr_tpu.nprocs()
    n = 8 * P
    src = np.full(n, 5.0, np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    with faults.injected("plan.flush", "program", times=1):
        with pytest.raises(resilience.ProgramError):
            with dr_tpu.deferred():
                dr_tpu.fill(a, 1.0)
                s = dr_tpu.reduce(a)
    # nothing executed: the container still holds its pre-region value
    np.testing.assert_array_equal(dr_tpu.to_numpy(a), src)
    with pytest.raises(RuntimeError):
        float(s)
    # the layer recovers: a fresh region works
    with dr_tpu.deferred():
        dr_tpu.fill(a, 2.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 2.0, np.float32))


def test_region_exception_discards_pending():
    P = dr_tpu.nprocs()
    n = 8 * P
    src = np.full(n, 3.0, np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    with pytest.raises(ValueError):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 9.0)
            raise ValueError("user error inside the region")
    np.testing.assert_array_equal(dr_tpu.to_numpy(a), src)
    assert dr_plan.active() is None
    assert any(e["reason"] == "region error" for e in p.log)


def test_explain_reports_runs_and_reasons():
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 1.0)
        dr_tpu.for_each(a, _shift, 1.0)
    txt = p.explain()
    assert "region exit" in txt and "fused run" in txt
    assert "fill" in txt and "transform" in txt
    st = p.stats()
    assert st == {"flushes": 1, "fused_runs": 1, "fused_ops": 2,
                  "opaque_ops": 0, "cache_hits": st["cache_hits"],
                  "dispatches": 1,
                  "opt": {"merged_runs": 0, "dce_ops": 0,
                          "pushdowns": 0}}


def test_nested_deferred_reenters():
    P = dr_tpu.nprocs()
    n = 8 * P
    a = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as outer:
        dr_tpu.fill(a, 1.0)
        with dr_tpu.deferred() as inner:
            dr_tpu.for_each(a, _shift, 1.0)
        assert inner is outer
        # inner exit must NOT flush: still one pending fused run
        assert outer.stats()["flushes"] == 0
    assert outer.stats()["flushes"] == 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 2.0, np.float32))


def test_mesh_change_splits_runs():
    """Containers on different meshes cannot share one program: the
    planner splits the run at the mesh change (round-5 review rule)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    rt_small = dr_tpu.init(jax.devices()[:2])
    small = dr_tpu.distributed_vector(8, np.float32, runtime=rt_small)
    rt_big = dr_tpu.init(jax.devices()[:4])
    big = dr_tpu.distributed_vector(16, np.float32, runtime=rt_big)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(small, 1.0)
        dr_tpu.fill(big, 2.0)
    assert p.stats()["fused_runs"] == 2
    np.testing.assert_array_equal(dr_tpu.to_numpy(small),
                                  np.full(8, 1.0, np.float32))
    np.testing.assert_array_equal(dr_tpu.to_numpy(big),
                                  np.full(16, 2.0, np.float32))


def test_plan_cache_is_tapped_for_guard():
    """Plan flush dispatches ride the spmd_guard trace like every other
    dispatch (the cache is a TappedCache)."""
    P = dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(8 * P, np.float32)
    with spmd_guard.guard() as g:
        with dr_tpu.deferred():
            dr_tpu.fill(a, 1.0)
    assert len(g.trace) == 1 and g.trace[0].startswith("(")


def test_persistent_compile_cache_wiring(tmp_path, monkeypatch):
    """DR_TPU_COMPILE_CACHE_DIR wires jax's persistent compilation
    cache at init (round 8): the config points at the directory and
    the min-compile-time threshold drops to zero so tunneled sessions
    stop re-paying compiles across processes."""
    from dr_tpu.parallel import runtime as rt
    prior_dir = jax.config.jax_compilation_cache_dir
    prior_min = jax.config.jax_persistent_cache_min_compile_time_secs
    path = str(tmp_path / "xla_cache")
    monkeypatch.setenv("DR_TPU_COMPILE_CACHE_DIR", path)
    monkeypatch.setattr(rt, "_compile_cache_wired", False)
    try:
        wired = rt.setup_compile_cache()
        assert wired == path
        assert jax.config.jax_compilation_cache_dir == path
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
        import os
        assert os.path.isdir(path)
        # idempotent: a second init call does not re-wire
        assert rt.setup_compile_cache() == path
    finally:
        jax.config.update("jax_compilation_cache_dir", prior_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prior_min)
        monkeypatch.setattr(rt, "_compile_cache_wired", False)


def test_compile_cache_unset_is_noop(monkeypatch):
    from dr_tpu.parallel import runtime as rt
    monkeypatch.delenv("DR_TPU_COMPILE_CACHE_DIR", raising=False)
    monkeypatch.setattr(rt, "_compile_cache_wired", False)
    assert rt.setup_compile_cache() is None


# ----------------------------------------------- redistribute fusion (§18.3)

def _half(x):
    return x * 0.5


def test_deferred_redistribute_fuses_without_flush():
    """ISSUE 12 acceptance: a collective-eligible redistribute RECORDS
    into the deferred plan — one fused run, ONE dispatch, no
    non-fusible flush cliff, no fallback warn — and the final physical
    layout is bit-identical to the eager sequence."""
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    team = [n] + [0] * (P - 1)

    ve = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.fill(ve, 2.0)
    dr_tpu.redistribute(ve, team)
    dr_tpu.for_each(ve, _half)
    want = float(dr_tpu.reduce(ve))

    vd = dr_tpu.distributed_vector.from_array(src)
    with warnings.catch_warnings():
        warnings.simplefilter("error", fallback.MaterializeFallbackWarning)
        d0 = spmd_guard.dispatch_count()
        with dr_tpu.deferred() as p:
            dr_tpu.fill(vd, 2.0)
            dr_tpu.redistribute(vd, team)
            dr_tpu.for_each(vd, _half)
            tot = dr_tpu.reduce(vd)
        used = spmd_guard.dispatch_count() - d0
    assert used <= 1, p.explain()
    assert float(tot) == want == n
    st = p.stats()
    assert st["fused_runs"] == 1 and st["fused_ops"] == 4, p.explain()
    assert vd.distribution is not None \
        and vd.distribution.sizes[0] == n
    np.testing.assert_array_equal(np.asarray(vd._data),
                                  np.asarray(ve._data))


def test_deferred_redistribute_layout_visible_to_later_records():
    """Ops recorded AFTER an in-plan redistribute key on the DST
    geometry (the metadata flips at record time): a subsequent
    host-array copy into the re-laid-out vector lands exactly as the
    eager sequence's."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("needs >= 2 shards for a layout change")
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    fresh = (np.arange(n, dtype=np.float32) * 3 + 1)
    uneven = [1] * (P - 1) + [n - (P - 1)]

    ve = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.redistribute(ve, uneven)
    dr_tpu.copy(fresh, ve)

    vd = dr_tpu.distributed_vector.from_array(src)
    with dr_tpu.deferred():
        dr_tpu.redistribute(vd, uneven)
        dr_tpu.copy(fresh, vd)
    np.testing.assert_array_equal(np.asarray(vd._data),
                                  np.asarray(ve._data))
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), fresh)


def test_deferred_redistribute_faulted_flush_rolls_back_metadata():
    """A fault at the flush boundary drops the queue — including the
    recorded re-layout's METADATA flip, which must undo so the
    container keeps its pre-flush layout AND value (the faulted-flush
    contract extended to §18.3's deferred rebind)."""
    P = dr_tpu.nprocs()
    n = 4 * P
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    with faults.injected("plan.flush", "program", times=1):
        with pytest.raises(resilience.ProgramError):
            with dr_tpu.deferred():
                dr_tpu.fill(v, 5.0)
                dr_tpu.redistribute(v, [n] + [0] * (P - 1))
    assert v.distribution is None
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_deferred_redistribute_host_route_flushes_announced():
    """A cross-runtime (host-staged) redistribute inside a region is a
    NON-FUSIBLE cliff: the plan flushes announced (warn_fallback) and
    the move runs eagerly — layout bookkeeping stays consistent."""
    import jax as _jax
    from jax.sharding import Mesh
    from dr_tpu.parallel.runtime import Runtime

    devs = _jax.devices()
    if len(devs) < 3:
        pytest.skip("needs >= 3 devices for a distinct sub-mesh")
    small = Runtime(mesh=Mesh(np.asarray(devs[1:3]), ("x",)))
    n = 12
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        with dr_tpu.deferred() as p:
            dr_tpu.fill(v, 1.5)
            dr_tpu.redistribute(v, None, runtime=small)
    assert v.runtime is small
    np.testing.assert_array_equal(dr_tpu.to_numpy(v),
                                  np.full(n, 1.5, np.float32))
    reasons = [e["reason"] for e in p.log]
    assert any("non-fusible" in r for r in reasons), reasons
