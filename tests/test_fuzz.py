"""Randomized property harness: random sizes / windows / ops vs numpy.

TPU analog of the reference's MPI-aware libFuzzer harness
(``test/fuzz/cpu/cpu-fuzz.cpp:50-64`` + ``algorithms.cpp:10-57``): a spec
(algorithm, n, b, e) drives copy/transform/reduce/scan over random
subranges, asserting against the serial result.  Seeded and bounded so it
runs deterministically in CI; crank DR_TPU_FUZZ_ITERS for longer runs.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import dr_tpu
from dr_tpu import views
from dr_tpu.utils.env import env_int, env_override, env_raw

# CI default trimmed 40 -> 28 in round 8, 28 -> 24 in round 19: the
# tier-1 suite keeps growing to the edge of its 870 s budget on the
# throttled container, and the fuzz arms are the compile-heaviest
# block.  Depth soaks stay with the crank (tools/fuzz_crank.sh runs
# every arm at 300 in its own process).
ITERS = env_int("DR_TPU_FUZZ_ITERS", 24, floor=0)  # 0 = skip the arms


def _mk(rng, n):
    src = rng.standard_normal(n).astype(np.float32)
    return src, dr_tpu.distributed_vector.from_array(src)


# module-level ops: program-cache keys pin callable identity, so fuzz
# loops must not mint fresh lambdas per iteration — the DR_TPU_SANITIZE
# run caught the old in-loop lambdas recompiling the same canonical
# program every pass (recompile churn, rule R1's identity-keyed twin)
def _twice_plus1(x):
    return x * 2 + 1


def _half_minus2(x):
    return x * 0.5 - 2


def _swap_sumdiff(x, y):
    return (x + y, x - y)


def _absdiff(x, y):
    return jnp.abs(x - y)


def _mul_plus1(x, y):
    return x * y + 1


@pytest.mark.parametrize("seed", [0, 1, pytest.param(2, marks=pytest.mark.slow), pytest.param(3, marks=pytest.mark.slow)])
def test_fuzz_subrange_ops(seed):
    rng = np.random.default_rng(seed)
    for it in range(ITERS):
        n = int(rng.integers(1, 200))
        b = int(rng.integers(0, n))
        e = int(rng.integers(b, n))
        alg = rng.choice(["copy", "transform", "reduce", "scan", "fill",
                          "iota", "sort"])
        src, dv = _mk(rng, n)
        if alg == "copy":
            dst_src, dst = _mk(rng, n)
            dr_tpu.copy(dv[b:e], dst[b:e])
            ref = dst_src.copy()
            ref[b:e] = src[b:e]
            np.testing.assert_allclose(dr_tpu.to_numpy(dst), ref,
                                       rtol=1e-5, atol=1e-6)
        elif alg == "transform":
            dst_src, dst = _mk(rng, n)
            dr_tpu.transform(dv[b:e], dst[b:e], _twice_plus1)
            ref = dst_src.copy()
            ref[b:e] = src[b:e] * 2 + 1
            np.testing.assert_allclose(dr_tpu.to_numpy(dst), ref,
                                       rtol=1e-5, atol=1e-6)
        elif alg == "reduce":
            got = dr_tpu.reduce(dv[b:e])
            np.testing.assert_allclose(
                got, float(src[b:e].astype(np.float64).sum()),
                rtol=1e-3, atol=1e-4)
        elif alg == "scan":
            out = dr_tpu.distributed_vector(n)
            dr_tpu.inclusive_scan(dv, out)
            np.testing.assert_allclose(dr_tpu.to_numpy(out),
                                       np.cumsum(src, dtype=np.float32),
                                       rtol=1e-3, atol=1e-4)
        elif alg == "fill":
            dr_tpu.fill(dv[b:e], 3.25)
            ref = src.copy()
            ref[b:e] = 3.25
            np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref)
        elif alg == "iota":
            iv = dr_tpu.distributed_vector(n, dtype=np.int32)
            dr_tpu.iota(iv[b:e], 5)
            ref = np.zeros(n, np.int32)
            ref[b:e] = np.arange(5, 5 + (e - b))
            np.testing.assert_array_equal(dr_tpu.to_numpy(iv), ref)
        elif alg == "sort":
            desc = bool(rng.integers(0, 2))
            mode = int(rng.integers(0, 3))
            if mode == 0:    # sample-sort fast path
                dr_tpu.sort(dv, descending=desc)
                ref = np.sort(src)[::-1] if desc else np.sort(src)
            elif mode == 1:  # window fallback
                dr_tpu.sort(dv[b:e], descending=desc)
                ref = src.copy()
                w = np.sort(ref[b:e])
                ref[b:e] = w[::-1] if desc else w
            else:            # stable key-value form
                pay = np.arange(n, dtype=np.float32)
                pv = dr_tpu.distributed_vector.from_array(pay)
                dr_tpu.sort_by_key(dv, pv, descending=desc)
                order = np.argsort(src, kind="stable")
                if desc:
                    order = order[::-1]
                ref = src[order]
                np.testing.assert_array_equal(dr_tpu.to_numpy(pv),
                                              pay[order])
            np.testing.assert_array_equal(dr_tpu.to_numpy(dv), ref)


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_zip_pipelines(seed):
    rng = np.random.default_rng(100 + seed)
    for it in range(ITERS // 2):
        n = int(rng.integers(2, 150))
        a_src, a = _mk(rng, n)
        b_src, b = _mk(rng, n)
        mode = rng.choice(["dot", "for_each", "tr"])
        if mode == "dot":
            got = dr_tpu.dot(a, b)
            ref = float(np.dot(a_src.astype(np.float64),
                               b_src.astype(np.float64)))
            assert got == pytest.approx(ref, rel=1e-3, abs=1e-3)
        elif mode == "for_each":
            z = views.zip_view(a, b)
            dr_tpu.for_each(z, _swap_sumdiff)
            np.testing.assert_allclose(dr_tpu.to_numpy(a), a_src + b_src,
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(dr_tpu.to_numpy(b), a_src - b_src,
                                       rtol=1e-5, atol=1e-6)
        else:
            got = dr_tpu.transform_reduce(
                views.transform(views.zip_view(a, b), _absdiff))
            ref = float(np.abs(a_src - b_src).astype(np.float64).sum())
            assert got == pytest.approx(ref, rel=1e-3, abs=1e-3)


def test_zip_ops_identity_stable_no_recompile():
    """Regression for the sanitizer's first true positive (round 10):
    the zip fuzz loops minted fresh lambdas per iteration, so the SAME
    canonical program recompiled every pass (identity-keyed recompile
    churn — DR_TPU_SANITIZE flagged 3 compiles of one canonical key in
    one epoch).  With module-level ops, a second pass over fresh
    containers of the same geometry must be cache-warm."""
    from dr_tpu.utils import sanitize
    rng = np.random.default_rng(7)
    n = 48
    a_src, a = _mk(rng, n)
    b_src, b = _mk(rng, n)
    dr_tpu.for_each(views.zip_view(a, b), _swap_sumdiff)  # compile once
    got = dr_tpu.transform_reduce(
        views.transform(views.zip_view(a, b), _absdiff))
    assert np.isfinite(got)
    with sanitize.zero_recompile("second pass, fresh containers"):
        c_src, c = _mk(rng, n)
        d_src, d = _mk(rng, n)
        dr_tpu.for_each(views.zip_view(c, d), _swap_sumdiff)
        np.testing.assert_allclose(dr_tpu.to_numpy(c), c_src + d_src,
                                   rtol=1e-5, atol=1e-6)
        # after the in-place swap: c = c0+d0, d = c0-d0, so |c-d| = |2*d0|
        got2 = dr_tpu.transform_reduce(
            views.transform(views.zip_view(c, d), _absdiff))
        ref = float(np.abs(2.0 * d_src).astype(np.float64).sum())
        assert got2 == pytest.approx(ref, rel=1e-3, abs=1e-3)


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_distributions(seed):
    """Random block distributions (incl. zero-size team blocks): the
    elementwise/reduce/scan surface must match numpy regardless of where
    the blocks fall."""
    rng = np.random.default_rng(300 + seed)
    P = dr_tpu.nprocs()
    for it in range(ITERS // 2):
        n = int(rng.integers(1, 160))
        cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
        bounds = np.concatenate(([0], cuts, [n]))
        sizes = tuple(int(b - a) for a, b in zip(bounds[:-1], bounds[1:]))
        src = rng.standard_normal(n).astype(np.float32)
        dv = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
        alg = rng.choice(["roundtrip", "transform", "reduce", "scan",
                          "sort", "putget", "axpy", "cscan"])
        if alg == "roundtrip":
            np.testing.assert_allclose(dr_tpu.to_numpy(dv), src,
                                       rtol=1e-6)
            segs = dr_tpu.segments(dv)
            assert [len(s) for s in segs] == [s for s in sizes if s]
        elif alg == "transform":
            out = dr_tpu.distributed_vector(n, np.float32,
                                            distribution=sizes)
            dr_tpu.transform(dv, out, _half_minus2)
            np.testing.assert_allclose(dr_tpu.to_numpy(out),
                                       src * 0.5 - 2, rtol=1e-5,
                                       atol=1e-6)
        elif alg == "reduce":
            got = dr_tpu.reduce(dv)
            np.testing.assert_allclose(
                got, float(src.astype(np.float64).sum()),
                rtol=1e-3, atol=1e-4)
        elif alg == "scan":
            out = dr_tpu.distributed_vector(n, np.float32,
                                            distribution=sizes)
            dr_tpu.inclusive_scan(dv, out)
            np.testing.assert_allclose(dr_tpu.to_numpy(out),
                                       np.cumsum(src, dtype=np.float32),
                                       rtol=1e-3, atol=1e-4)
        elif alg == "sort":
            # sample sort over the random (team-bearing) distribution
            dr_tpu.sort(dv)
            np.testing.assert_array_equal(dr_tpu.to_numpy(dv),
                                          np.sort(src))
            assert dr_tpu.is_sorted(dv)
        elif alg == "cscan":
            # identityless custom op over the uneven distribution:
            # round 4 runs these NATIVELY (inclusive and exclusive)
            out = dr_tpu.distributed_vector(n, np.float32,
                                            distribution=sizes)
            excl = bool(rng.integers(0, 2))
            if excl:
                dr_tpu.exclusive_scan(dv, out, init=None, op=_fuzz_chain)
            else:
                dr_tpu.inclusive_scan(dv, out, op=_fuzz_chain)
            ref = np.empty(n, np.float32)
            acc = src[0]
            ref[0] = acc
            for i in range(1, n):
                acc = np.float32(acc + src[i]
                                 + acc * src[i] * np.float32(0.25))
                ref[i] = acc
            if excl:
                ref = np.concatenate(
                    [[np.float32(0.0)], ref[:-1]]).astype(np.float32)
            np.testing.assert_allclose(dr_tpu.to_numpy(out), ref,
                                       rtol=2e-3, atol=2e-3)
        elif alg == "axpy":
            # traced scalar over an uneven distribution: same-layout zip
            p_src = rng.standard_normal(n).astype(np.float32)
            pv = dr_tpu.distributed_vector.from_array(
                p_src, distribution=sizes)
            alpha = float(rng.standard_normal())
            dr_tpu.transform(views.zip(dv, pv), dv, _fuzz_axpy, alpha)
            np.testing.assert_allclose(
                dr_tpu.to_numpy(dv),
                src + np.float32(alpha) * p_src, rtol=1e-5, atol=1e-5)
        else:
            k = int(rng.integers(1, min(8, n) + 1))
            idx = rng.choice(n, size=k, replace=False)
            vals = rng.standard_normal(k).astype(np.float32)
            dv.put(idx, vals)
            np.testing.assert_allclose(np.asarray(dv.get(idx)), vals,
                                       rtol=1e-6)
            ref = src.copy()
            ref[idx] = vals
            np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref,
                                       rtol=1e-6)


def test_fuzz_halo_stencil():
    rng = np.random.default_rng(7)
    for it in range(8):
        P = dr_tpu.nprocs()
        n = int(rng.integers(4 * P, 12 * P))
        r = int(rng.integers(1, 3))
        periodic = bool(rng.integers(0, 2))
        tail = n - (P - 1) * max(-(-n // P), r)
        if tail < max(r, 1):
            continue
        src = rng.standard_normal(n).astype(np.float32)
        hb = dr_tpu.halo_bounds(r, r, periodic)
        try:
            a = dr_tpu.distributed_vector.from_array(src, halo=hb)
            b = dr_tpu.distributed_vector.from_array(src, halo=hb)
        except ValueError:
            continue
        w = rng.random(2 * r + 1).astype(np.float64)
        w /= w.sum()
        out = dr_tpu.stencil_iterate(a, b, list(w), steps=2)
        ref = src.astype(np.float64)
        for _ in range(2):
            if periodic:
                acc = np.zeros_like(ref)
                for d in range(-r, r + 1):
                    acc += np.roll(ref, -d) * w[d + r]
                ref = acc
            else:
                y = ref.copy()
                acc = np.zeros(n - 2 * r)
                for d in range(-r, r + 1):
                    acc += ref[r + d:n - r + d] * w[d + r]
                y[r:n - r] = acc
                ref = y
        np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-3,
                                   atol=1e-4)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_gemv(seed):
    """Random sparsity patterns through both SpMV paths (ELL and
    segment_sum), accumulate semantics, vs a numpy scatter oracle."""
    rng = np.random.default_rng(100 + seed)
    for _ in range(6):
        m = int(rng.integers(4, 60))
        ncols = int(rng.integers(3, 40))
        nnz = int(rng.integers(0, 4 * m + 1))
        rows = rng.integers(0, m, size=nnz)
        cols = rng.integers(0, ncols, size=nnz)
        vals = rng.standard_normal(nnz).astype(np.float32)
        A = dr_tpu.sparse_matrix.from_coo((m, ncols), rows, cols, vals)
        bsrc = rng.standard_normal(ncols).astype(np.float32)
        csrc = rng.standard_normal(m).astype(np.float32)
        c = dr_tpu.distributed_vector.from_array(csrc)
        b = dr_tpu.distributed_vector.from_array(bsrc)
        dr_tpu.gemv(c, A, b)
        ref = csrc.astype(np.float64)
        np.add.at(ref, rows, vals.astype(np.float64) * bsrc[cols])
        np.testing.assert_allclose(dr_tpu.to_numpy(c), ref, rtol=1e-3,
                                   atol=1e-4)


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_scans(seed):
    """Random lengths/ops through inclusive/exclusive scan vs numpy
    accumulate.  One case per seed is large enough that every shard's
    local scan takes the blocked / MXU-cumsum formulation (> 2*1024
    elements per shard on the 8-device mesh)."""
    rng = np.random.default_rng(200 + seed)
    cases = [
        (None, np.add.accumulate),
        (jnp.maximum, np.maximum.accumulate),
        (jnp.multiply, np.multiply.accumulate),
    ]
    sizes = [(int(rng.integers(3, 5000)), None) for _ in range(3)]
    # deterministic blocked/MXU-cumsum case: big enough per shard and
    # pinned to the add op (a random multiply draw would be clamped
    # below the blocked threshold)
    sizes.append((8 * 2 ** 11 * 2 + int(rng.integers(1, 99)), 0))
    for n, forced in sizes:
        op, acc = cases[int(rng.integers(0, len(cases)))
                        if forced is None else forced]
        if op is jnp.multiply:
            # keep magnitudes near 1 so the oracle tail stays far above
            # atol (otherwise the comparison is vacuous)
            n = min(n, 500)
            src = rng.uniform(0.9, 1.1, n).astype(np.float32)
        else:
            src = rng.uniform(0.5, 1.5, n).astype(np.float32)
        a = dr_tpu.distributed_vector.from_array(src)
        out = dr_tpu.distributed_vector(n)
        dr_tpu.inclusive_scan(a, out, op=op)
        np.testing.assert_allclose(dr_tpu.to_numpy(out),
                                   acc(src.astype(np.float64)),
                                   rtol=2e-3, atol=1e-3)
        if op is None:
            ex = dr_tpu.distributed_vector(n)
            dr_tpu.exclusive_scan(a, ex)
            ref = np.concatenate(
                [[0.0], np.cumsum(src.astype(np.float64))[:-1]])
            np.testing.assert_allclose(dr_tpu.to_numpy(ex), ref,
                                       rtol=2e-3, atol=1e-3)


def test_fuzz_cyclic_dense_roundtrip_and_gemm():
    """Randomized cyclic layouts: fold/unfold roundtrip + gemm oracle
    (the reference fuzz harness's random-subrange spirit applied to the
    round-2 multi-tile storage)."""
    rng = np.random.default_rng(77)
    for _ in range(8):
        m = int(rng.integers(4, 40))
        n = int(rng.integers(4, 40))
        th = int(rng.integers(1, 9))
        tw = int(rng.integers(1, 9))
        gp, gq = dr_tpu.factor(dr_tpu.nprocs())
        part = dr_tpu.block_cyclic(tile=(th, tw), grid=(gp, gq))
        src = rng.standard_normal((m, n)).astype(np.float32)
        mat = dr_tpu.dense_matrix.from_array(src, part)
        np.testing.assert_array_equal(mat.materialize(), src)
        segs = dr_tpu.segments(mat)
        total = sum((s.re - s.rb) * (s.ce - s.cb) for s in segs)
        assert total == m * n
        other = rng.standard_normal((n, 8)).astype(np.float32)
        B = dr_tpu.dense_matrix.from_array(other)
        C = dr_tpu.gemm(mat, B)
        np.testing.assert_allclose(C.materialize(), src @ other,
                                   rtol=1e-4, atol=1e-4)


def test_fuzz_sparse_2d_gemv():
    rng = np.random.default_rng(78)
    gp, gq = dr_tpu.factor(dr_tpu.nprocs())
    part = dr_tpu.block_cyclic(grid=(gp, gq))
    for _ in range(6):
        m = int(rng.integers(gp, 60))
        n = int(rng.integers(gq, 60))
        d = np.where(rng.random((m, n)) < 0.3,
                     rng.standard_normal((m, n)), 0).astype(np.float32)
        sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
        b = rng.standard_normal(n).astype(np.float32)
        c = dr_tpu.distributed_vector(m)
        dr_tpu.fill(c, 0.0)
        dr_tpu.gemv(c, sp, b)
        np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b,
                                   rtol=1e-4, atol=1e-4)


def _fuzz_axpy(x, p, alpha):
    return x + alpha * p


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_scalar_transforms(seed):
    """Trailing traced scalars over random zip windows: one cached
    program per op regardless of the coefficient stream."""
    rng = np.random.default_rng(300 + seed)
    for it in range(ITERS // 2):
        n = int(rng.integers(2, 150))
        b = int(rng.integers(0, n - 1))
        e = int(rng.integers(b + 1, n))
        a_src, a = _mk(rng, n)
        p_src, p = _mk(rng, n)
        alpha = float(rng.standard_normal())
        dr_tpu.transform(views.zip(a[b:e], p[b:e]), a[b:e],
                         _fuzz_axpy, alpha)
        ref = a_src.copy()
        ref[b:e] = a_src[b:e] + np.float32(alpha) * p_src[b:e]
        np.testing.assert_allclose(dr_tpu.to_numpy(a), ref,
                                   rtol=1e-5, atol=1e-5)


def test_fuzz_matmul_stencil_band_widths(monkeypatch):
    """Every composed-block size across band widths D=1..4 against the
    serial oracle (the multi-column P-form's index arithmetic)."""
    rng = np.random.default_rng(77)
    w = [0.05, 0.25, 0.4, 0.25, 0.05]  # radius 2
    r = 2
    P = dr_tpu.nprocs()
    # D = 1, 1, 1, 2, 2, 3, 4, 5 — the last case exceeds the 4-column
    # default cap so the DR_TPU_MM_BAND_COLS widening path stays covered
    for k in (8, 32, 64, 96, 128, 192, 256, 320):
        halo = max(128, -(-k * r // 128) * 128)
        n = P * 1024
        src = rng.standard_normal(n).astype(np.float32)
        hb = dr_tpu.halo_bounds(halo, halo, periodic=True)
        dv = dr_tpu.distributed_vector.from_array(src, halo=hb)
        steps = int(rng.integers(1, 3)) * k  # whole blocks
        from dr_tpu.algorithms.stencil import stencil_iterate_matmul
        import dr_tpu.ops.stencil_matmul as sm
        if k > sm.max_ksteps(r):
            monkeypatch.setenv("DR_TPU_MM_BAND_COLS", "8")
        out = stencil_iterate_matmul(dv, w, steps, k_block=k)
        x = src.astype(np.float64)
        for _ in range(steps):
            x = sum(wi * np.roll(x, s)
                    for wi, s in zip(w, (2, 1, 0, -1, -2)))
        np.testing.assert_allclose(dr_tpu.to_numpy(out), x,
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_halo_exchange_reduce(seed):
    """Random (prev, next, periodic, n) through exchange + reduce — the
    comm-layer layout edges (asymmetric radii, short tails, ring wrap)
    vs a logical-index oracle (VERDICT r2 item 7).

    Semantics: after exchange every ghost mirrors its logical neighbor
    element; reduce(op) folds each ghost's value back into the cell it
    mirrors (halo.hpp:73-110)."""
    rng = np.random.default_rng(400 + seed)
    P = dr_tpu.nprocs()
    for _ in range(ITERS // 3):
        prev = int(rng.integers(0, 4))
        nxt = int(rng.integers(0, 4))
        if prev == 0 and nxt == 0:
            continue
        periodic = bool(rng.integers(0, 2))
        n = int(rng.integers(2 * P, 14 * P))
        src = rng.standard_normal(n).astype(np.float32)
        hb = dr_tpu.halo_bounds(prev, nxt, periodic)
        try:
            dv = dr_tpu.distributed_vector.from_array(src, halo=hb)
        except ValueError:
            continue  # shards too small for this halo (min-size check)
        dr_tpu.halo(dv).exchange()
        seg = dv.segment_size
        rows = np.asarray(dv._data)
        # ghost oracle: logical neighbors, wrap only when periodic
        for r in range(dv.nshards):
            lo = r * seg
            hi = min(n, lo + seg)
            if prev and (r > 0 or periodic):
                want = src[(np.arange(lo - prev, lo)) % n]
                np.testing.assert_allclose(rows[r, :prev], want,
                                           err_msg=f"ghost_prev r={r}")
            if nxt and (r < dv.nshards - 1 or periodic):
                # wrap only under periodic; without it, ghost cells
                # past the logical end are unspecified (the documented
                # short-tail contract) and must not be asserted
                idx = np.arange(hi, hi + nxt)
                k = nxt if periodic else int((idx < n).sum())
                want = src[idx[:k] % n]
                # a short tail places its incoming ghost right after the
                # owned cells (stencils read x[i+1] at prev+tail), not
                # at the padded prev+seg slot
                tail = hi - lo
                np.testing.assert_allclose(
                    rows[r, prev + tail:prev + tail + k], want,
                    err_msg=f"ghost_next r={r}")
        # reduce oracle: every live ghost adds into the cell it mirrors
        dr_tpu.halo(dv).reduce_plus()
        ref = src.astype(np.float64).copy()
        for r in range(dv.nshards):
            lo = r * seg
            hi = min(n, lo + seg)
            if prev and (r > 0 or periodic):
                for g in range(lo - prev, lo):
                    ref[g % n] += src[g % n]
            if nxt and (r < dv.nshards - 1 or periodic):
                for g in range(hi, hi + nxt):
                    if periodic or g < n:
                        ref[g % n] += src[g % n]
        np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref, rtol=1e-5,
                                   atol=1e-5)


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_unstructured_halo(seed):
    """Random index maps through the unstructured halo: exchange must
    mirror owners into ghosts, and scatter-reduce must combine every
    contribution — including DUPLICATE indices across ranks (the case
    the reference's sequential unpack loop hides, halo.hpp:181-203)."""
    rng = np.random.default_rng(500 + seed)
    P = dr_tpu.nprocs()
    for _ in range(ITERS // 4):
        n = int(rng.integers(P, 20 * P))
        src = rng.standard_normal(n).astype(np.float32)
        dv = dr_tpu.distributed_vector.from_array(src)
        ghost_map = {}
        for r in range(P):
            k = int(rng.integers(0, min(n, 6) + 1))
            if k:
                ghost_map[r] = rng.integers(0, n, size=k).tolist()
        uh = dr_tpu.unstructured_halo(dv, ghost_map)
        uh.exchange()
        for r, ix in ghost_map.items():
            np.testing.assert_allclose(np.asarray(uh.ghost_values(r)),
                                       src[np.asarray(ix)], rtol=1e-6)
        # each rank writes contributions into its ghosts, then reduce
        contribs = {}
        for r, ix in ghost_map.items():
            vals = rng.standard_normal(len(ix)).astype(np.float32)
            contribs[r] = vals
            uh.set_ghost_values(r, vals)
        uh.reduce("plus")
        ref = src.astype(np.float64).copy()
        for r, ix in ghost_map.items():
            np.add.at(ref, np.asarray(ix), contribs[r].astype(np.float64))
        np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref, rtol=1e-5,
                                   atol=1e-5)


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_misaligned_zip_fallback(seed):
    """Zips of differently-distributed operands: ``aligned()`` must
    report False and every algorithm must still produce the serial
    result through the resharding fallback (the reference falls back to
    rank-0 serial RMA, cpu_algorithms.hpp:44-54; ours reshards)."""
    rng = np.random.default_rng(600 + seed)
    P = dr_tpu.nprocs()
    for _ in range(ITERS // 4):
        n = int(rng.integers(P, 80))

        def cuts():
            c = np.sort(rng.integers(0, n + 1, size=P - 1))
            b = np.concatenate(([0], c, [n]))
            return tuple(int(y - x) for x, y in zip(b[:-1], b[1:]))

        da, db = cuts(), cuts()
        a_src = rng.standard_normal(n).astype(np.float32)
        b_src = rng.standard_normal(n).astype(np.float32)
        a = dr_tpu.distributed_vector.from_array(a_src, distribution=da)
        b = dr_tpu.distributed_vector.from_array(b_src, distribution=db)
        if da != db:
            assert not dr_tpu.aligned(a, b)
        out = dr_tpu.distributed_vector(n)  # uniform: misaligned w/ both
        dr_tpu.transform(views.zip(a, b), out, _mul_plus1)
        np.testing.assert_allclose(dr_tpu.to_numpy(out),
                                   a_src * b_src + 1, rtol=1e-5,
                                   atol=1e-5)
        got = dr_tpu.dot(a, b)
        ref = float(a_src.astype(np.float64) @ b_src.astype(np.float64))
        assert got == pytest.approx(ref, rel=1e-3, abs=1e-3)


def _fuzz_chain(a, b):
    """Unclassified (identityless) fold for the distribution fuzz."""
    return a + b + a * b * np.float32(0.25)


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_spmm(seed):
    """Multi-vector SpMM over random patterns, nv widths, and (banded)
    BCSR-eligible shapes vs the dense oracle."""
    rng = np.random.default_rng(900 + seed)
    for it in range(max(4, ITERS // 6)):
        m = int(rng.integers(8, 200))
        nn = int(rng.integers(8, 200))
        nv = int(rng.integers(1, 7))
        k = int(rng.integers(1, 6))
        rows = np.repeat(np.arange(m), k)
        cols = rng.integers(0, nn, size=m * k)
        vals = rng.standard_normal(m * k).astype(np.float32)
        A = dr_tpu.sparse_matrix.from_coo((m, nn), rows, cols, vals)
        B = rng.standard_normal((nn, nv)).astype(np.float32)
        dense = np.zeros((m, nn), np.float32)
        np.add.at(dense, (rows, cols), vals)
        got = np.asarray(dr_tpu.spmm(A, B))
        np.testing.assert_allclose(got, dense @ B, rtol=2e-4,
                                   atol=2e-4)
        # chained-measurement program agrees with the one-shot product
        got_n = np.asarray(dr_tpu.spmm_n(A, B, int(rng.integers(1, 4))))
        np.testing.assert_allclose(got_n, got, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_expr_grammar(seed):
    """The expr-DSL validator is a trust boundary (the bridge feeds it
    serialized strings): VALID generated expressions must compile and
    match a numpy oracle; random junk must be REJECTED with ValueError
    — never reach eval with non-DSL content (round 5; native twin:
    fuzz_native arm_expr_dsl)."""
    import string

    from dr_tpu.utils import expr as ex
    rng = np.random.default_rng(500 + seed)

    FN1 = {"abs": np.abs, "sqrt": lambda v: np.sqrt(np.abs(v) + 1.0)}
    FN2 = {"minimum": np.minimum, "maximum": np.maximum}

    def gen(depth, nargs):
        r = rng.integers(0, 6 if depth > 0 else 2)
        if r == 0:
            i = int(rng.integers(0, nargs))
            return f"x{i}", lambda vs, i=i: vs[i]
        if r == 1:
            c = round(float(rng.uniform(-4, 4)), 3)
            return repr(c), lambda vs, c=c: np.float32(c)
        if r in (2, 3):
            op = rng.choice(["+", "-", "*"])
            ls, lf = gen(depth - 1, nargs)
            rs, rf = gen(depth - 1, nargs)
            f = {"+": np.add, "-": np.subtract,
                 "*": np.multiply}[str(op)]
            return (f"({ls} {op} {rs})",
                    lambda vs, lf=lf, rf=rf, f=f: f(lf(vs), rf(vs)))
        if r == 4:
            name = str(rng.choice(list(FN2)))
            ls, lf = gen(depth - 1, nargs)
            rs, rf = gen(depth - 1, nargs)
            return (f"{name}({ls}, {rs})",
                    lambda vs, lf=lf, rf=rf, f=FN2[name]: f(lf(vs),
                                                            rf(vs)))
        name = "abs"  # sqrt of negatives would NaN the oracle: abs only
        ls, lf = gen(depth - 1, nargs)
        return (f"{name}({ls})",
                lambda vs, lf=lf: np.abs(lf(vs)))

    for _ in range(ITERS):
        nargs = int(rng.integers(1, 4))
        s, oracle = gen(int(rng.integers(1, 4)), nargs)
        fn = ex.op_from_expr(s, nargs)
        vs = [rng.standard_normal(8).astype(np.float32)
              for _ in range(nargs)]
        got = np.asarray(fn(*[jnp.asarray(v) for v in vs]))
        np.testing.assert_allclose(got, oracle(vs).astype(np.float32),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"expr: {s}")

    # junk must be rejected, not evaluated: non-DSL names, stray
    # punctuation, dunders, out-of-range args
    alphabet = string.ascii_letters + string.digits + "()+-*/., _'\"[]"
    for _ in range(ITERS * 3):
        junk = "".join(rng.choice(list(alphabet))
                       for _ in range(int(rng.integers(1, 30))))
        try:
            ex.op_from_expr(junk, 2)
        except (ValueError, SyntaxError):
            continue
        # anything accepted must genuinely be inside the grammar:
        # names only x0/x1 + whitelisted functions, DSL chars only
        import re
        names = set(re.findall(r"[A-Za-z_][A-Za-z_0-9]*", junk))
        allowed = {"x0", "x1"} | set(ex.FUNCTIONS)
        assert all(n in allowed or re.fullmatch(r"[eE]\d*", n)
                   for n in names), f"accepted junk: {junk!r}"
        assert "__" not in junk
    # targeted escapes stay closed
    for bad in ("__import__('os')", "x0.__class__", "x9", "lambda: 1",
                "x0 ; x1", "open('/etc/passwd')", "x0\n+x1", "x0,x1"):
        with pytest.raises((ValueError, SyntaxError)):
            ex.op_from_expr(bad, 2)


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow), pytest.param(2, marks=pytest.mark.slow)])
def test_fuzz_round5_window_shapes(seed):
    """Round-5 native shapes under random geometry: window pairs of ONE
    container for sort_by_key (disjoint, overlapping, nested, equal),
    mismatched in/out scan windows, and identityless custom reduces —
    all vs numpy oracles, all with materialize disarmed."""
    rng = np.random.default_rng(700 + seed)
    real = dr_tpu.distributed_vector.to_array

    def boom(self):
        raise AssertionError("round-5 native shape materialized")

    for it in range(ITERS):
        n = int(rng.integers(4, 160))
        src = rng.standard_normal(n).astype(np.float32)
        case = rng.choice(["kv_windows", "scan_mismatch", "reduce"])
        if case == "kv_windows":
            wn = int(rng.integers(1, n // 2 + 1))
            ka = int(rng.integers(0, n - wn + 1))
            va = int(rng.integers(0, n - wn + 1))
            x = dr_tpu.distributed_vector.from_array(src)
            dr_tpu.distributed_vector.to_array = boom
            try:
                dr_tpu.sort_by_key(x[ka:ka + wn], x[va:va + wn])
            finally:
                dr_tpu.distributed_vector.to_array = real
            ref = src.copy()
            order = np.argsort(src[ka:ka + wn], kind="stable")
            ref[ka:ka + wn] = src[ka:ka + wn][order]
            ref[va:va + wn] = src[va:va + wn][order]
            np.testing.assert_array_equal(
                dr_tpu.to_numpy(x), ref,
                err_msg=f"kv n={n} ka={ka} va={va} wn={wn}")
        elif case == "scan_mismatch":
            wn = int(rng.integers(1, n + 1))
            ia = int(rng.integers(0, n - wn + 1))
            oa = int(rng.integers(0, n - wn + 1))
            a = dr_tpu.distributed_vector.from_array(src)
            aliased = bool(rng.integers(0, 2))
            out = a if aliased \
                else dr_tpu.distributed_vector.from_array(0.0 * src)
            dr_tpu.distributed_vector.to_array = boom
            try:
                dr_tpu.inclusive_scan(a[ia:ia + wn], out[oa:oa + wn])
            finally:
                dr_tpu.distributed_vector.to_array = real
            base = src if aliased else 0.0 * src
            ref = base.copy()
            ref[oa:oa + wn] = np.cumsum(src[ia:ia + wn])
            np.testing.assert_allclose(
                dr_tpu.to_numpy(out), ref, rtol=1e-4, atol=1e-4,
                err_msg=f"scan n={n} ia={ia} oa={oa} wn={wn} "
                        f"aliased={aliased}")
        else:
            pos = np.abs(src) * 0.2 + 0.9
            v = dr_tpu.distributed_vector.from_array(pos)
            wn = int(rng.integers(1, n + 1))
            b = int(rng.integers(0, n - wn + 1))
            dr_tpu.distributed_vector.to_array = boom
            try:
                got = dr_tpu.reduce(v[b:b + wn],
                                    op=_CUSTOM_MUL)
            finally:
                dr_tpu.distributed_vector.to_array = real
            np.testing.assert_allclose(
                got,
                float(np.prod(pos[b:b + wn].astype(np.float64))),
                rtol=1e-3, err_msg=f"reduce n={n} b={b} wn={wn}")


_CUSTOM_MUL = lambda a, b: a * b * 1.0  # defined once: program reuse


def _fuzz_shift(x, mu):
    """Monotone BoundOp for the is_sorted view-chain arm."""
    return x + mu


def _np_is_sorted(a):
    """numpy-order sortedness oracle (NaNs largest, ties fine)."""
    return np.array_equal(np.sort(a), a, equal_nan=True)


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_sort_family(seed):
    """Round-6 sort-family arm (tools/fuzz_crank.sh): random geometry,
    dtypes, NaNs, tie density, windows, mixed distributions, and
    aliased window pairs through sort / sort_by_key / argsort /
    is_sorted vs numpy oracles — the crank discipline that caught real
    bugs in rounds 4 and 5, pointed at the restructured single-exchange
    hot path.  CI default runs ITERS // 2 per seed (each iteration
    compiles fresh geometry — the heaviest arm in the file); cranks set
    DR_TPU_FUZZ_ITERS explicitly (tools/fuzz_crank.sh 300
    sort_family)."""
    rng = np.random.default_rng(800 + seed)
    P = dr_tpu.nprocs()

    def dist(n):
        if P < 2 or not rng.integers(0, 2):
            return None
        cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
        b = np.concatenate(([0], cuts, [n]))
        return tuple(int(y - x) for x, y in zip(b[:-1], b[1:]))

    def mkvec(src, d):
        if d is None:
            return dr_tpu.distributed_vector.from_array(src)
        return dr_tpu.distributed_vector.from_array(src, distribution=d)

    def keysrc(n):
        kind = int(rng.integers(0, 3))
        if kind == 0:
            src = rng.standard_normal(n).astype(np.float32)
            if rng.integers(0, 4) == 0:
                src[rng.integers(0, n, size=max(1, n // 8))] = np.nan
            return src
        if kind == 1:  # heavy ties: the stability surface
            return rng.integers(0, 5, n).astype(np.float32)
        return rng.integers(-40, 40, n).astype(np.int32)

    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None else ITERS // 2
    for it in range(iters):
        n = int(rng.integers(1, 170))
        desc = bool(rng.integers(0, 2))
        case = str(rng.choice(["sort", "sort_win", "kv", "kv_win",
                               "kv_alias", "argsort", "is_sorted"]))
        tag = f"{case} n={n} desc={desc} it={it}"
        if case == "sort":
            src = keysrc(n)
            v = mkvec(src, dist(n))
            dr_tpu.sort(v, descending=desc)
            ref = np.sort(src)
            np.testing.assert_array_equal(
                dr_tpu.to_numpy(v), ref[::-1] if desc else ref,
                err_msg=tag)
        elif case == "sort_win":
            src = keysrc(n)
            b = int(rng.integers(0, n))
            e = int(rng.integers(b, n + 1))
            v = mkvec(src, dist(n))
            dr_tpu.sort(v[b:e], descending=desc)
            ref = src.copy()
            w = np.sort(src[b:e])
            ref[b:e] = w[::-1] if desc else w
            np.testing.assert_array_equal(dr_tpu.to_numpy(v), ref,
                                          err_msg=tag)
        elif case in ("kv", "kv_win"):
            k = keysrc(n)
            pay = (np.arange(n, dtype=np.int32)
                   if rng.integers(0, 2)
                   else rng.standard_normal(n).astype(np.float32))
            kd = mkvec(k, dist(n))
            vd = mkvec(pay, dist(n))  # distributions MAY differ
            if case == "kv":
                dr_tpu.sort_by_key(kd, vd, descending=desc)
                order = np.argsort(k, kind="stable")
                if desc:
                    order = order[::-1]
                np.testing.assert_array_equal(dr_tpu.to_numpy(kd),
                                              k[order], err_msg=tag)
                np.testing.assert_array_equal(dr_tpu.to_numpy(vd),
                                              pay[order], err_msg=tag)
            else:
                wn = int(rng.integers(1, n + 1))
                ka = int(rng.integers(0, n - wn + 1))
                va = int(rng.integers(0, n - wn + 1))
                dr_tpu.sort_by_key(kd[ka:ka + wn], vd[va:va + wn],
                                   descending=desc)
                order = np.argsort(k[ka:ka + wn], kind="stable")
                if desc:
                    order = order[::-1]
                kref = k.copy()
                kref[ka:ka + wn] = k[ka:ka + wn][order]
                pref = pay.copy()
                pref[va:va + wn] = pay[va:va + wn][order]
                np.testing.assert_array_equal(dr_tpu.to_numpy(kd),
                                              kref, err_msg=tag)
                np.testing.assert_array_equal(dr_tpu.to_numpy(vd),
                                              pref, err_msg=tag)
        elif case == "kv_alias":
            # two windows of ONE container: disjoint, nested,
            # overlapping, or equal — blends compose payload-last
            src = rng.standard_normal(n).astype(np.float32)
            wn = int(rng.integers(1, n + 1))
            ka = int(rng.integers(0, n - wn + 1))
            va = int(rng.integers(0, n - wn + 1))
            x = mkvec(src, dist(n))
            dr_tpu.sort_by_key(x[ka:ka + wn], x[va:va + wn],
                               descending=desc)
            order = np.argsort(src[ka:ka + wn], kind="stable")
            if desc:
                order = order[::-1]
            ref = src.copy()
            ref[ka:ka + wn] = src[ka:ka + wn][order]
            ref[va:va + wn] = src[va:va + wn][order]
            np.testing.assert_array_equal(dr_tpu.to_numpy(x), ref,
                                          err_msg=tag)
        elif case == "argsort":
            src = keysrc(n)
            v = mkvec(src, dist(n))
            idx = dr_tpu.argsort(v, descending=desc)
            order = np.argsort(src, kind="stable")
            if desc:
                order = order[::-1]
            np.testing.assert_array_equal(dr_tpu.to_numpy(idx), order,
                                          err_msg=tag)
            # the input is untouched
            np.testing.assert_array_equal(dr_tpu.to_numpy(v), src,
                                          err_msg=tag)
        else:  # is_sorted, whole + windowed + view chain
            src = np.sort(keysrc(n))
            if rng.integers(0, 2) and n > 1:
                src[int(rng.integers(0, n))] = src.min() - 1 \
                    if np.isfinite(src.min()) else np.float32(0)
            v = mkvec(src, dist(n))
            got = dr_tpu.is_sorted(v)
            assert got == _np_is_sorted(src), tag
            b = int(rng.integers(0, n))
            e = int(rng.integers(b, n + 1))
            assert dr_tpu.is_sorted(v[b:e]) == _np_is_sorted(src[b:e]), \
                tag
            # monotone BoundOp chain: sortedness is invariant, and the
            # streamed coefficient must reuse one program (round 6)
            mu = float(rng.standard_normal())
            assert dr_tpu.is_sorted(
                views.transform(v, _fuzz_shift, mu)) == got, tag


# ---------------------------------------------------------------------------
# sparse-format fuzz (round 9 — ISSUE 4 satellite arm)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_sparse_formats(seed):
    """Round-9 sparse-format arm (tools/fuzz_crank.sh): every SpMV
    layout (CSR segment-sum / ELL / BCSR / ring) over random densities
    and grids — 1-D and 2-D tilings, an all-rows-empty matrix, a
    one-dense-row adversary (the ELL padding blowup the autoselect
    dodges), banded block structure, and ring-friendly spreads — each
    checked against a float64 dense oracle.  The ring schedule's two
    issue orders (serial / pipelined) are additionally compared
    BIT-for-bit whenever the layout is eligible: same dataflow, same
    reduction order, so any difference is a scheduling bug.  spmm rides
    the same sweep against the same oracle."""
    rng = np.random.default_rng(1000 + seed)
    P = dr_tpu.nprocs()
    gp, gq = dr_tpu.factor(P)
    iters = max(4, ITERS // 6)
    for it in range(iters):
        m = int(rng.integers(4, 120))
        nn = int(rng.integers(4, 120))
        kind = str(rng.choice(["uniform", "perrow", "empty",
                               "dense_row", "banded", "ringfriendly"]))
        if kind == "uniform":
            d = np.where(rng.random((m, nn)) < rng.uniform(0.02, 0.4),
                         rng.standard_normal((m, nn)), 0)
            rows, cols = np.nonzero(d)
            vals = d[rows, cols].astype(np.float32)
        elif kind == "perrow":
            k = int(rng.integers(1, 6))
            rows = np.repeat(np.arange(m), k)
            cols = rng.integers(0, nn, m * k)
            vals = rng.standard_normal(m * k).astype(np.float32)
        elif kind == "empty":
            rows = np.zeros(0, np.int64)
            cols = np.zeros(0, np.int64)
            vals = np.zeros(0, np.float32)
        elif kind == "dense_row":
            r0 = int(rng.integers(0, m))
            rows = np.concatenate([np.full(nn, r0, np.int64),
                                   rng.integers(0, m, 4)])
            cols = np.concatenate([np.arange(nn),
                                   rng.integers(0, nn, 4)])
            vals = rng.standard_normal(len(rows)).astype(np.float32)
        elif kind == "banded":
            half = int(rng.integers(1, 5))
            ii = np.repeat(np.arange(m), 2 * half + 1)
            jj = ii + np.tile(np.arange(-half, half + 1), m)
            keep = (jj >= 0) & (jj < nn)
            rows, cols = ii[keep], jj[keep]
            vals = rng.standard_normal(len(rows)).astype(np.float32)
        else:  # ringfriendly: k entries in k distinct b-blocks per row
            k = int(rng.integers(1, min(4, P) + 1))
            bw = max(1, -(-nn // P))
            rows = np.repeat(np.arange(m), k)
            blocks = np.tile(np.arange(k) % P, m)
            cols = np.minimum(blocks * bw
                              + rng.integers(0, bw, m * k), nn - 1)
            vals = rng.standard_normal(m * k).astype(np.float32)
        part = None
        if rng.integers(0, 2) and gq > 1:
            part = dr_tpu.block_cyclic(grid=(gp, gq))
        A = dr_tpu.sparse_matrix.from_coo((m, nn), rows, cols, vals,
                                          partition=part)
        dense = np.zeros((m, nn), np.float64)
        np.add.at(dense, (rows, cols), vals.astype(np.float64))
        b = rng.standard_normal(nn).astype(np.float32)
        ref = dense @ b.astype(np.float64)
        tag = f"seed={seed} it={it} kind={kind} m={m} nn={nn} " \
              f"grid={(gp, gq) if part else (P, 1)} auto={A.format}"

        def run_gemv():
            c = dr_tpu.distributed_vector(m)
            dr_tpu.fill(c, 0.0)
            dr_tpu.gemv(c, A, b)
            return dr_tpu.to_numpy(c)

        with env_override(DR_TPU_SPMV_FORMAT=None,
                          DR_TPU_RING_SCHEDULE=None):
            for fmt in ("csr", "ell", "bcsr", "ring"):
                os.environ["DR_TPU_SPMV_FORMAT"] = fmt
                np.testing.assert_allclose(
                    run_gemv(), ref, rtol=1e-3, atol=1e-4,
                    err_msg=f"{tag} fmt={fmt}")
            if part is None and A.ensure_ring():
                os.environ["DR_TPU_SPMV_FORMAT"] = "ring"
                outs = {}
                for sched in ("serial", "pipelined"):
                    os.environ["DR_TPU_RING_SCHEDULE"] = sched
                    outs[sched] = run_gemv()
                np.testing.assert_array_equal(
                    outs["serial"], outs["pipelined"],
                    err_msg=f"{tag}: ring schedules diverge")
        nv = int(rng.integers(1, 4))
        B = rng.standard_normal((nn, nv)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(dr_tpu.spmm(A, B)),
            dense @ B.astype(np.float64), rtol=1e-3, atol=1e-4,
            err_msg=f"{tag} spmm nv={nv}")


# ---------------------------------------------------------------------------
# deferred-plan op-chain fuzz (round 8 — ISSUE 3 satellite arm)
# ---------------------------------------------------------------------------

def _pf_scale(x, c):
    return x * c


def _pf_shift(x, c):
    return x + c


def _pf_mul2(x, y):
    return x * y


def _pf_swap(x, y):
    return (x + y, x - y)


@pytest.mark.parametrize("seed", range(2))
def test_fuzz_plan_chains(seed):
    """Round-8 deferred-plan arm (tools/fuzz_crank.sh): seeded random
    FUSIBLE op chains — fill/iota/for_each/transform/zip shapes/host
    copy/halo exchange+reduce/stencil step/reduce/dot, plus the opaque
    scan — over random sizes, halo widths, and mesh widths, recorded in
    one deferred region and BIT-compared against the same chain run
    eagerly (container contents and every scalar, exact).  One carve-out:
    chains containing a stencil step compare at <= 1 ULP — the stencil's
    internal multiply-add tree is FMA-contractable, and the backend may
    contract DIFFERENTLY in two different compilations of the same math
    (cross-op contraction is pinned by the plan's seal+barrier; within-op
    contraction variance is backend freedom, docs/SPEC.md "Deferred
    execution").  Each chain compiles one fresh plan program, so the arm
    runs ITERS // 4 per seed in CI; the crank gives it its own process
    like every arm."""
    import jax
    from dr_tpu.utils.spmd_guard import dispatch_count

    rng = np.random.default_rng(900 + seed)
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None else ITERS // 2
    for it in range(max(4, iters // 4)):
        P = min(int(rng.integers(1, 9)), len(jax.devices()))
        dr_tpu.init(jax.devices()[:P])
        hw = int(rng.integers(0, 3))
        periodic = bool(rng.integers(0, 2))
        if hw:
            # full uniform shards: every halo-constraint shape is legal
            n = P * int(rng.integers(max(2 * hw, 1), 13))
            hb = dr_tpu.halo_bounds(hw, hw, periodic=periodic)
        else:
            n = int(rng.integers(1, 97))
            hb = None
        src_a = rng.standard_normal(n).astype(np.float32)
        src_b = rng.standard_normal(n).astype(np.float32)
        ea = dr_tpu.distributed_vector.from_array(src_a, halo=hb)
        eb = dr_tpu.distributed_vector.from_array(src_b, halo=hb)
        da = dr_tpu.distributed_vector.from_array(src_a, halo=hb)
        db = dr_tpu.distributed_vector.from_array(src_b, halo=hb)

        kinds = ["fill", "iota", "foreach", "xform", "zipmul", "zipfe",
                 "copy", "reduce", "dot", "scan", "subfill"]
        if hw:
            kinds += ["exch", "hred", "stencil"]
        ops = [(str(rng.choice(kinds)),
                float(np.round(rng.standard_normal(), 3)),
                int(rng.integers(0, n + 1)), int(rng.integers(0, n + 1)))
               for _ in range(int(rng.integers(3, 9)))]
        tag = f"seed={seed} it={it} P={P} n={n} hw={hw} ops={ops}"

        def apply(a, b, kind, c, i0, i1):
            if kind == "fill":
                dr_tpu.fill(a, c)
            elif kind == "iota":
                dr_tpu.iota(b, int(c * 10))
            elif kind == "foreach":
                dr_tpu.for_each(a, _pf_scale, c)
            elif kind == "xform":
                dr_tpu.transform(a, b, _pf_shift, c)
            elif kind == "zipmul":
                dr_tpu.transform(views.zip(a, b), b, _pf_mul2)
            elif kind == "zipfe":
                dr_tpu.for_each(views.zip(a, b), _pf_swap)
            elif kind == "copy":
                dr_tpu.copy(np.full(n, c, np.float32), a)
            elif kind == "reduce":
                return dr_tpu.reduce(b)
            elif kind == "dot":
                return dr_tpu.dot(a, b)
            elif kind == "scan":
                dr_tpu.inclusive_scan(a, b)
            elif kind == "subfill":
                lo, hi = min(i0, i1), max(i0, i1)
                dr_tpu.fill(a[lo:hi], c)
            elif kind == "exch":
                dr_tpu.halo(a).exchange()
            elif kind == "hred":
                dr_tpu.halo(a).reduce_plus()
            elif kind == "stencil":
                dr_tpu.stencil_transform(a, b, [0.25, 0.5, 0.25][:2 * hw + 1]
                                         if hw == 1 else
                                         [0.1, 0.2, 0.4, 0.2, 0.1])
            return None

        want = [apply(ea, eb, *op) for op in ops]
        d0 = dispatch_count()
        with dr_tpu.deferred() as p:
            got = [apply(da, db, *op) for op in ops]
        used = dispatch_count() - d0
        eager_used = sum(1 for op in ops if op[0] != "reduce") + 1
        assert used <= eager_used + 1, f"{tag}: {used} dispatches"
        has_stencil = any(op[0] == "stencil" for op in ops)
        for w, g in zip(want, got):
            if w is not None:
                if has_stencil:
                    assert abs(float(g) - w) <= 1e-5 * max(1.0, abs(w)), \
                        f"{tag}: scalar {w} != {float(g)}"
                else:
                    assert float(g) == w, \
                        f"{tag}: scalar {w} != {float(g)}"
        for dv, ev in ((da, ea), (db, eb)):
            if has_stencil:
                # the contraction ULP can be amplified by later chain
                # ops (cancellation in x - y), so the carve-out is a
                # tolerance, not a ULP count
                np.testing.assert_allclose(
                    dr_tpu.to_numpy(dv), dr_tpu.to_numpy(ev),
                    rtol=1e-4, atol=1e-6, err_msg=tag)
            else:
                np.testing.assert_array_equal(
                    dr_tpu.to_numpy(dv), dr_tpu.to_numpy(ev),
                    err_msg=tag)
        del p


# ---------------------------------------------------------------------------
# cross-mesh fuzz (round 11 — VERDICT weak #5 / ROADMAP item 2 satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_cross_mesh(seed):
    """Round-11 cross-mesh arm (tools/fuzz_crank.sh): random SECOND
    runtimes over random device subsets drive the two-runtime reshard
    routes — sort_by_key with keys and payload on DIFFERENT meshes
    (mismatched shard counts AND equal counts over different device
    sets, windows and uneven distributions included) and scans whose
    input and output containers live on different meshes — against
    numpy oracles, with the materialize fallback DISARMED: the round-5
    reshard routes promise native collectives, so a
    MaterializeFallbackWarning here is a regression, not a slow path.
    The crank discipline that keeps catching real geometry bugs
    (rounds 4/5/6), finally pointed at the two-runtime dispatch
    (VERDICT weak #5)."""
    import jax

    from dr_tpu.parallel.runtime import Runtime
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("cross-mesh fuzz needs >= 2 devices")
    rng = np.random.default_rng(1600 + seed)

    def mk_runtime():
        p = int(rng.integers(1, len(devs) + 1))
        off = int(rng.integers(0, len(devs) - p + 1))
        return Runtime(mesh=Mesh(np.asarray(devs[off:off + p]), ("x",)))

    # a small pool per seed bounds the per-iteration compile load while
    # distributions/windows keep randomizing the geometry underneath
    pool = [None] + [mk_runtime() for _ in range(3)]  # None = default

    def dist(n, rt):
        P = rt.nprocs if rt is not None else dr_tpu.nprocs()
        if P < 2 or not rng.integers(0, 2):
            return None
        cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
        b = np.concatenate(([0], cuts, [n]))
        return tuple(int(y - x) for x, y in zip(b[:-1], b[1:]))

    def mkvec(src, rt):
        return dr_tpu.distributed_vector.from_array(
            src, distribution=dist(len(src), rt), runtime=rt)

    # CI default is ITERS // 4: every iteration sorts/scans on a FRESH
    # runtime pair, so programs recompile per pass — the second-
    # heaviest arm in the file; depth soaks belong to the crank
    # (tools/fuzz_crank.sh sets DR_TPU_FUZZ_ITERS explicitly)
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else ITERS // 4
    # the suite silences fallback warnings (conftest) — un-silence and
    # clear the once-per-site memory HERE, or the no-materialize
    # assertion below would be vacuous
    from dr_tpu.utils import fallback
    with env_override(DR_TPU_SILENCE_FALLBACKS=None):
        fallback.reset()
        try:
            _cross_mesh_iters(rng, pool, mkvec, iters, seed)
        finally:
            fallback.reset()


def _cross_mesh_iters(rng, pool, mkvec, iters, seed):
    import warnings

    from dr_tpu.utils.fallback import MaterializeFallbackWarning
    for it in range(iters):
        n = int(rng.integers(2, 150))
        rt_a, rt_b = rng.choice(len(pool), size=2, replace=False)
        rt_a, rt_b = pool[rt_a], pool[rt_b]
        case = str(rng.choice(["kv", "kv_win", "scan", "scan_win"]))
        desc = bool(rng.integers(0, 2))
        tag = f"cross-mesh {case} n={n} it={it} seed={seed}"
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            if case in ("kv", "kv_win"):
                k = rng.standard_normal(n).astype(np.float32)
                pay = (np.arange(n, dtype=np.int32)
                       if rng.integers(0, 2)
                       else rng.standard_normal(n).astype(np.float32))
                kd = mkvec(k, rt_a)
                vd = mkvec(pay, rt_b)
                if case == "kv":
                    dr_tpu.sort_by_key(kd, vd, descending=desc)
                    order = np.argsort(k, kind="stable")
                    if desc:
                        order = order[::-1]
                    np.testing.assert_array_equal(
                        dr_tpu.to_numpy(kd), k[order], err_msg=tag)
                    np.testing.assert_array_equal(
                        dr_tpu.to_numpy(vd), pay[order], err_msg=tag)
                else:
                    wn = int(rng.integers(1, n + 1))
                    ka = int(rng.integers(0, n - wn + 1))
                    va = int(rng.integers(0, n - wn + 1))
                    dr_tpu.sort_by_key(kd[ka:ka + wn], vd[va:va + wn],
                                       descending=desc)
                    order = np.argsort(k[ka:ka + wn], kind="stable")
                    if desc:
                        order = order[::-1]
                    kref, pref = k.copy(), pay.copy()
                    kref[ka:ka + wn] = k[ka:ka + wn][order]
                    pref[va:va + wn] = pay[va:va + wn][order]
                    np.testing.assert_array_equal(
                        dr_tpu.to_numpy(kd), kref, err_msg=tag)
                    np.testing.assert_array_equal(
                        dr_tpu.to_numpy(vd), pref, err_msg=tag)
            else:
                src = rng.standard_normal(n).astype(np.float32)
                base = rng.standard_normal(n).astype(np.float32)
                sv = mkvec(src, rt_a)
                out = mkvec(base, rt_b)
                if case == "scan":
                    dr_tpu.inclusive_scan(sv, out)
                    np.testing.assert_allclose(
                        dr_tpu.to_numpy(out),
                        np.cumsum(src, dtype=np.float32),
                        rtol=1e-4, atol=1e-5, err_msg=tag)
                else:
                    wn = int(rng.integers(1, n + 1))
                    sa = int(rng.integers(0, n - wn + 1))
                    oa = int(rng.integers(0, n - wn + 1))
                    dr_tpu.inclusive_scan(sv[sa:sa + wn],
                                          out[oa:oa + wn])
                    ref = base.copy()
                    ref[oa:oa + wn] = np.cumsum(src[sa:sa + wn],
                                                dtype=np.float32)
                    np.testing.assert_allclose(
                        dr_tpu.to_numpy(out), ref, rtol=1e-4,
                        atol=1e-5, err_msg=tag)
                # the INPUT is untouched by a cross-mesh scan
                np.testing.assert_array_equal(dr_tpu.to_numpy(sv), src,
                                              err_msg=tag)
        bad = [str(r.message) for r in rec
               if issubclass(r.category, MaterializeFallbackWarning)]
        assert not bad, f"{tag}: materialize fallback regressed: {bad}"


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_redistribute(seed):
    """Round-13 redistribute arm (tools/fuzz_crank.sh; seeds ROADMAP
    item 2): random src -> dst redistributions — random explicit block
    distributions (zero-size teams and uneven cuts included) and
    random TARGET runtimes over random device subsets — must preserve
    the logical value bit-for-bit against the numpy oracle across
    every hop, and algorithms must keep answering on the final layout
    (reduce vs numpy sum).  The host-staged v1 is the contract the
    collective lowering must keep."""
    import jax

    from dr_tpu.parallel.runtime import Runtime
    from jax.sharding import Mesh

    devs = jax.devices()
    rng = np.random.default_rng(1700 + seed)

    def mk_runtime():
        p = int(rng.integers(1, len(devs) + 1))
        off = int(rng.integers(0, len(devs) - p + 1))
        return Runtime(mesh=Mesh(np.asarray(devs[off:off + p]), ("x",)))

    pool = [None] + [mk_runtime() for _ in range(3)]  # None = default

    def dist(n, rt):
        P = rt.nprocs if rt is not None else dr_tpu.nprocs()
        roll = int(rng.integers(0, 3))
        if P < 2 or roll == 0:
            return None
        if roll == 1:  # team: everything on one random rank
            sizes = [0] * P
            sizes[int(rng.integers(0, P))] = n
            return tuple(sizes)
        cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
        b = np.concatenate(([0], cuts, [n]))
        return tuple(int(y - x) for x, y in zip(b[:-1], b[1:]))

    # fresh runtimes recompile pack/extract per layout: CI runs a
    # slice, the crank sets DR_TPU_FUZZ_ITERS explicitly
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else ITERS // 4
    for it in range(iters):
        n = int(rng.integers(1, 200))
        src = rng.standard_normal(n).astype(np.float32)
        rt0 = pool[int(rng.integers(0, len(pool)))]
        v = dr_tpu.distributed_vector.from_array(
            src, distribution=dist(n, rt0), runtime=rt0)
        for hop in range(int(rng.integers(1, 3))):
            rt = pool[int(rng.integers(0, len(pool)))]
            dr_tpu.redistribute(v, dist(n, rt), runtime=rt)
            np.testing.assert_array_equal(dr_tpu.to_numpy(v), src,
                                          err_msg=f"it={it} hop={hop}")
        got = float(dr_tpu.reduce(v))
        want = float(src.astype(np.float64).sum())
        assert abs(got - want) <= 1e-3 * max(1.0, abs(want)), \
            f"it={it}: reduce {got} vs {want}"


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_redistribute_impls(seed):
    """Round-16 collective-vs-host BIT-equality arm (tools/fuzz_crank.sh;
    ISSUE 12): random same-mesh src -> dst re-layouts — uneven cuts,
    zero-size team blocks, halo vectors, several dtypes — forced
    through BOTH impls via the ``DR_TPU_REDISTRIBUTE`` override.  The
    physical padded rows (not just the logical values) must match
    bit-for-bit: the collective exchange program's contract is 'the
    host-staged v1, without the host'."""
    rng = np.random.default_rng(1900 + seed)
    P = dr_tpu.nprocs()
    dtypes = [np.float32, np.int32, np.float16, np.uint8]

    def dist(n):
        roll = int(rng.integers(0, 3))
        if P < 2 or roll == 0:
            return None
        if roll == 1:  # team: everything on one random rank
            sizes = [0] * P
            sizes[int(rng.integers(0, P))] = n
            return tuple(sizes)
        cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
        b = np.concatenate(([0], cuts, [n]))
        return tuple(int(y - x) for x, y in zip(b[:-1], b[1:]))

    # fresh layout pairs compile an exchange program each (and the
    # single-core CI container prices every XLA compile in wall
    # time): CI runs a thin slice, the crank sets DR_TPU_FUZZ_ITERS
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else max(ITERS // 8, 3)
    for it in range(iters):
        n = int(rng.integers(1, 200))
        dt = dtypes[int(rng.integers(0, len(dtypes)))]
        src = (rng.standard_normal(n) * 50).astype(dt)
        hb = None
        d0 = dist(n)
        if d0 is None and rng.random() < 0.3:
            hb = dr_tpu.halo_bounds(1, 1, periodic=True)
        va = dr_tpu.distributed_vector.from_array(src, halo=hb,
                                                  distribution=d0)
        vb = dr_tpu.distributed_vector.from_array(src, halo=hb,
                                                  distribution=d0)
        for hop in range(int(rng.integers(1, 4))):
            # halo vectors keep the uniform-layout constructor contract
            d = None if hb is not None else dist(n)
            with env_override(DR_TPU_REDISTRIBUTE="collective"):
                dr_tpu.redistribute(va, d)
            with env_override(DR_TPU_REDISTRIBUTE="host"):
                dr_tpu.redistribute(vb, d)
            tag = f"it={it} hop={hop} dt={np.dtype(dt)} d={d}"
            np.testing.assert_array_equal(
                np.asarray(va._data), np.asarray(vb._data),
                err_msg=f"{tag}: physical rows diverged")
            np.testing.assert_array_equal(dr_tpu.to_numpy(va), src,
                                          err_msg=tag)


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_join_partition(seed):
    """Round-16 repartition-join arm (ISSUE 12, docs/SPEC.md §18.4):
    random key distributions (uniform / skewed / all-equal / distinct /
    float, NaNs included) x uneven input layouts through BOTH join
    merge routes — the broadcast sorted-merge and the bounded-memory
    repartition exchange forced via ``DR_TPU_JOIN_BROADCAST_MAX=0`` —
    must agree BIT-for-bit on every output channel and the row count,
    for inner/left/right/outer alike; the partition route must also
    report a gathered channel bounded by the full right side."""
    from dr_tpu.algorithms import relational as _rel
    rng = np.random.default_rng(2100 + seed)
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("the repartition route needs >= 2 shards")
    # every iteration compiles fresh probe + partition + broadcast
    # programs (single-core CI container): CI runs a thin slice, the
    # crank sets DR_TPU_FUZZ_ITERS explicitly
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else max(ITERS // 8, 3)
    for it in range(iters):
        nl = int(rng.integers(1, 100))
        nr = int(rng.integers(1, 100))
        kind = rng.choice(["uniform", "skewed", "all_equal",
                           "distinct", "float"])
        kl = _fuzz_rel_keys(rng, nl, kind)
        kr = _fuzz_rel_keys(rng, nr, kind)
        if kind == "float" and rng.random() < 0.5:
            kl[::5] = np.nan
            kr[::7] = np.nan
        vl = rng.standard_normal(nl).astype(np.float32)
        vr = rng.standard_normal(nr).astype(np.float32)
        how = ("inner", "left", "right", "outer")[it % 4]
        cap = nl * nr + nl + nr + 1
        tag = f"it={it} how={how} kind={kind} nl={nl} nr={nr}"

        def run(thresh):
            a = dr_tpu.distributed_vector.from_array(
                kl, distribution=_fuzz_rel_dist(rng, nl, P))
            b = dr_tpu.distributed_vector.from_array(vl)
            c = dr_tpu.distributed_vector.from_array(
                kr, distribution=_fuzz_rel_dist(rng, nr, P))
            d = dr_tpu.distributed_vector.from_array(vr)
            ok = dr_tpu.distributed_vector(cap)
            ol = dr_tpu.distributed_vector(cap)
            orr = dr_tpu.distributed_vector(cap)
            with env_override(DR_TPU_JOIN_BROADCAST_MAX=thresh):
                m = dr_tpu.join(a, b, c, d, ok, ol, orr, how=how,
                                fill=-7.5)
            return (int(m), dr_tpu.to_numpy(ok), dr_tpu.to_numpy(ol),
                    dr_tpu.to_numpy(orr))

        mb, okb, olb, orb = run("999999999")
        assert _rel.last_join_route()["impl"] == "broadcast", tag
        mp, okp, olp, orp = run("0")
        route = _rel.last_join_route()
        assert route["impl"] == "partition", tag
        # the gathered channel is the rcap-bounded partition, never
        # more than the padded full right side (uniform keys shrink it
        # well below — the dedicated regression asserts that).  Use
        # the ROUTE's own side sizes: a right join swaps the sides,
        # so the partitioned 'right' is the caller's left.
        NR = route["nshards"] \
            * -(-max(route["nr"], 1) // route["nshards"])
        assert route["rcap"] <= NR, (tag, route)
        assert mb == mp, f"{tag}: rows {mb} != {mp}"
        np.testing.assert_array_equal(okb, okp, err_msg=f"{tag} keys")
        np.testing.assert_array_equal(olb, olp, err_msg=f"{tag} left")
        np.testing.assert_array_equal(orb, orp, err_msg=f"{tag} right")


# ---------------------------------------------------------------------------
# RELATIONAL arm (round 14, ISSUE 10): random key distributions
# (uniform / skewed / all-equal / distinct / float) x uneven layouts
# (zero-size team blocks included) through join / groupby / unique /
# histogram / top_k vs pandas/numpy oracles — the composite tier's
# crank discipline (docs/SPEC.md §17).
# ---------------------------------------------------------------------------

def _fuzz_rel_keys(rng, n, kind):
    if kind == "all_equal":
        return np.full(n, float(rng.integers(0, 5)), np.float32)
    if kind == "distinct":
        return rng.permutation(n).astype(np.float32)
    if kind == "skewed":
        # a heavy head + a long tail (zipf-ish): most rows share one
        # key, the rest scatter
        k = np.where(rng.random(n) < 0.7, 0.0,
                     rng.integers(1, max(n // 4, 2), n))
        return k.astype(np.float32)
    if kind == "float":
        return np.round(rng.standard_normal(n) * 2).astype(np.float32)
    return rng.integers(0, max(n // 3, 2), n).astype(np.float32)


def _fuzz_rel_dist(rng, n, P):
    if rng.random() < 0.5:
        return None  # default uniform ceil layout
    cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
    bounds = np.concatenate(([0], cuts, [n]))
    return tuple(int(b - a) for a, b in zip(bounds[:-1], bounds[1:]))


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_relational(seed):
    import pandas as pd
    rng = np.random.default_rng(1400 + seed)
    P = dr_tpu.nprocs()
    for it in range(ITERS):
        n = int(rng.integers(1, 140))
        kind = rng.choice(["uniform", "skewed", "all_equal",
                           "distinct", "float"])
        keys = _fuzz_rel_keys(rng, n, kind)
        vals = rng.standard_normal(n).astype(np.float32)
        kv = dr_tpu.distributed_vector.from_array(
            keys, distribution=_fuzz_rel_dist(rng, n, P))
        vv = dr_tpu.distributed_vector.from_array(
            vals, distribution=_fuzz_rel_dist(rng, n, P))
        alg = rng.choice(["groupby", "unique", "histogram", "top_k",
                          "join"])
        tag = f"it={it} alg={alg} kind={kind} n={n}"
        if alg == "groupby":
            agg = rng.choice(["sum", "min", "max", "count", "mean"])
            ok = dr_tpu.distributed_vector(
                n, np.float32, distribution=_fuzz_rel_dist(rng, n, P))
            ov = dr_tpu.distributed_vector(n, np.float32)
            ng = dr_tpu.groupby_aggregate(kv, vv, ok, ov, agg=agg)
            ref = getattr(pd.DataFrame({"k": keys, "v": vals})
                          .groupby("k")["v"], agg)()
            assert ng == len(ref), tag
            np.testing.assert_array_equal(
                dr_tpu.to_numpy(ok)[:ng],
                ref.index.values.astype(np.float32), err_msg=tag)
            np.testing.assert_allclose(
                dr_tpu.to_numpy(ov)[:ng],
                ref.values.astype(np.float32), rtol=1e-4, atol=1e-5,
                err_msg=tag)
        elif alg == "unique":
            out = dr_tpu.distributed_vector(n, np.float32)
            nu = dr_tpu.unique(kv, out)
            ref = np.unique(keys)
            assert nu == len(ref), tag
            np.testing.assert_array_equal(dr_tpu.to_numpy(out)[:nu],
                                          ref, err_msg=tag)
        elif alg == "histogram":
            bins = int(rng.integers(1, 12))
            lo, hi = -2.5, float(rng.uniform(0.5, 3.0))
            out = dr_tpu.distributed_vector(
                bins, np.int32,
                distribution=_fuzz_rel_dist(rng, bins, P))
            dr_tpu.histogram(vv, out, lo, hi)
            x = vals.astype(np.float64)
            inr = (x >= lo) & (x <= hi)
            b = np.minimum(np.floor((x[inr] - lo) * bins / (hi - lo))
                           .astype(np.int64), bins - 1)
            np.testing.assert_array_equal(
                dr_tpu.to_numpy(out), np.bincount(b, minlength=bins),
                err_msg=tag)
        elif alg == "top_k":
            k = int(rng.integers(1, n + 4))
            tv = dr_tpu.distributed_vector(k, np.float32)
            ti = dr_tpu.distributed_vector(k, np.int32)
            largest = bool(rng.integers(0, 2))
            dr_tpu.top_k(vv, tv, ti, largest=largest)
            gv = dr_tpu.to_numpy(tv)
            gi = dr_tpu.to_numpy(ti)
            kk = min(k, n)
            ref = np.sort(vals)[::-1][:kk] if largest \
                else np.sort(vals)[:kk]
            np.testing.assert_allclose(gv[:kk], ref, err_msg=tag)
            np.testing.assert_array_equal(vals[gi[:kk]], gv[:kk],
                                          err_msg=tag)
            assert len(set(gi[:kk].tolist())) == kk, tag
        else:  # join
            nr = int(rng.integers(1, 100))
            rkeys = _fuzz_rel_keys(
                rng, nr, rng.choice(["uniform", "all_equal",
                                     "distinct"]))
            rvals = rng.standard_normal(nr).astype(np.float32)
            rkv = dr_tpu.distributed_vector.from_array(
                rkeys, distribution=_fuzz_rel_dist(rng, nr, P))
            rvv = dr_tpu.distributed_vector.from_array(rvals)
            how = rng.choice(["inner", "left", "right", "outer"])
            ref = pd.merge(pd.DataFrame({"k": keys, "lv": vals}),
                           pd.DataFrame({"k": rkeys, "rv": rvals}),
                           on="k", how=how).fillna(-7.0)
            cap = max(len(ref), 1)
            jk = dr_tpu.distributed_vector(
                cap, np.float32,
                distribution=_fuzz_rel_dist(rng, cap, P))
            jl = dr_tpu.distributed_vector(cap, np.float32)
            jr = dr_tpu.distributed_vector(cap, np.float32)
            m = dr_tpu.join(kv, vv, rkv, rvv, jk, jl, jr, how=how,
                            fill=-7.0)
            assert m == len(ref), tag
            got = pd.DataFrame({"k": dr_tpu.to_numpy(jk)[:m],
                                "lv": dr_tpu.to_numpy(jl)[:m],
                                "rv": dr_tpu.to_numpy(jr)[:m]})
            a = got.sort_values(["k", "lv", "rv"]) \
                .reset_index(drop=True)
            b = ref.sort_values(["k", "lv", "rv"]) \
                .reset_index(drop=True)
            np.testing.assert_allclose(a.values,
                                       b.values.astype(np.float32),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=tag)


# ---------------------------------------------------------------------------
# KILL-AND-REVIVE arm (round 15, ISSUE 11): random elastic
# shrink → grow-back sequences over random container populations —
# the symmetric-elasticity crank discipline (docs/SPEC.md §16.6).
# Collected by tools/fuzz_crank.sh with the fuzz arms.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(2))
def test_fuzz_elastic_kill_and_revive(seed, tmp_path):
    """Kill a random rank, then REVIVE it through ``grow_session``:
    every rescued/restored container must ride the re-admission
    bit-equal to its pre-fault oracle, a container the shrink LOST
    must stay classified across the grow (never resurrected as a
    silent wrong answer), and the re-grown session must keep
    computing.  Random populations: team vectors dodging (or not) the
    dead rank, uneven cuts, checkpointed defaults, a per-tile-restored
    dense matrix."""
    import jax

    from dr_tpu.utils import elastic, resilience, sanitize

    all_devs = jax.devices()
    if len(all_devs) < 2:
        pytest.skip("shrink needs >= 2 devices")
    rng = np.random.default_rng(1850 + seed)
    # fresh + shrunken + grown meshes recompile per pass: CI runs a
    # slice, the crank sets DR_TPU_FUZZ_ITERS explicitly
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else max(2, ITERS // 14)
    for it in range(iters):
        P = int(rng.integers(2, len(all_devs) + 1))
        dr_tpu.init(all_devs[:P])
        elastic.reset()
        if sanitize.installed():
            # each pass re-layouts the same canonical programs onto
            # fresh meshes (init → shrink → grow) — one sanitize
            # epoch per pass, or the legitimate re-layout recompiles
            # read as a storm
            sanitize.reset_epoch()
        lost = int(rng.integers(0, P))
        pop = []  # (container, oracle, may_be_lost)
        for k in range(int(rng.integers(1, 4))):
            n = int(rng.integers(1, 48))
            src = rng.standard_normal(n).astype(np.float32)
            shape = rng.integers(0, 3)
            if shape == 0:  # team on one random rank
                sizes = [0] * P
                home = int(rng.integers(0, P))
                sizes[home] = n
                c = dr_tpu.distributed_vector.from_array(
                    src, distribution=sizes)
                pop.append((c, src, home == lost))
            elif shape == 1:  # checkpointed default: always restorable
                c = dr_tpu.distributed_vector.from_array(src)
                dr_tpu.checkpoint.save(
                    str(tmp_path / f"kr{seed}_{it}_{k}.npz"), c)
                pop.append((c, src, False))
            else:  # bare default: lost iff it owns the dead rank
                c = dr_tpu.distributed_vector.from_array(src)
                b, e = c._rank_window(lost)
                pop.append((c, src, b < e))
        msrc = rng.standard_normal((2 * P, 2)).astype(np.float32)
        mat = dr_tpu.dense_matrix.from_array(msrc, dr_tpu.row_tiles())
        dr_tpu.checkpoint.save(str(tmp_path / f"kr{seed}_{it}_m.npz"),
                               mat)

        rep = elastic.rescue_session(resilience.DeviceLostError(
            f"fuzz kill {it}", rank=lost))
        assert rep.nprocs_after == P - 1
        grown = elastic.grow_session(reason=f"fuzz revive {it}")
        assert grown.nprocs_after >= P
        assert dr_tpu.nprocs() >= P
        assert grown.kept == 0, grown.fates

        survived = 0
        for c, oracle, may_lose in pop:
            try:
                got = dr_tpu.to_numpy(c)
            except resilience.DeviceLostError:
                assert may_lose, \
                    f"it={it}: a rescuable container was lost"
                continue
            survived += 1
            np.testing.assert_allclose(got, oracle, rtol=1e-6,
                                       err_msg=f"it={it}")
        # +1: the checkpointed matrix always lands in restored (its
        # tile grid spans every rank, so the dead rank always hits)
        assert survived + 1 == rep.rescued + rep.restored
        np.testing.assert_array_equal(mat.materialize(), msrc,
                                      err_msg=f"it={it}")
        # the re-grown session still computes correctly
        w = dr_tpu.distributed_vector.from_array(
            np.ones(2 * dr_tpu.nprocs(), np.float32))
        assert abs(float(dr_tpu.reduce(w)) - len(w)) < 1e-4


# ---------------------------------------------------------------------------
# plan-optimizer bit-identity fuzz (round 19 — ISSUE 15, docs/SPEC.md §21)
# ---------------------------------------------------------------------------

def _po_scale(x, c):
    return x * c


def _po_shift(x, c):
    return x + c


@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_plan_opt(seed, tmp_path):
    """Round-19 plan-optimizer arm (tools/fuzz_crank.sh): seeded
    random recorded chains — fusible transforms / fills / reduce /
    dot / histogram / top_k / redistribute / the opaque scan / the
    relational auto ops (join_auto, groupby_auto, unique_auto) — each
    flushed TWICE on fresh containers, ``DR_TPU_PLAN_OPT=all`` vs
    ``=0``, and compared BIT-for-bit: every container, every resolved
    scalar, every relational count and trimmed row set.  The §21
    contract under test is bit-identity-by-construction for EVERY
    pass (merge / dce / pushdown / capinfer / joinroute), so any
    difference is an optimizer bug, not tolerance noise.  A slice of
    iterations additionally injects a mid-flush device loss under
    ``DR_TPU_ELASTIC=1`` on the optimized arm: the shrink-and-rescue
    replay must land the values the unoptimized no-fault arm produced
    — exactly for integer channels, at the elastic suite's tolerance
    for float ones (a shrink changes the MESH WIDTH, so psum trees
    and scan carries regroup; cross-width FP identity is impossible
    and §21.3 scopes bit-identity to a fixed mesh).  The crank
    re-runs this arm under ``DR_TPU_SANITIZE=1``
    and with per-pass ``DR_TPU_PLAN_OPT_DISABLE`` bisection (the
    PLAN-OPT arm; drlint R7 keys the pass registry on it)."""
    import jax

    from dr_tpu import faults, tuning
    from dr_tpu.plan import opt as plan_opt

    rng = np.random.default_rng(1900 + seed)
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else ITERS // 2
    # per-pass bisection: most passes armed, one randomly disabled per
    # iteration sometimes — every registered pass name cycles through
    pass_names = plan_opt.PASS_NAMES
    for it in range(max(4, iters // 6)):
        P = min(int(rng.integers(1, 9)), len(jax.devices()))
        dr_tpu.init(jax.devices()[:P])
        n = int(rng.integers(8, 65))
        nk = int(rng.integers(4, 49))
        srcs = {
            "a": rng.standard_normal(n).astype(np.float32),
            "b": rng.standard_normal(n).astype(np.float32),
            "k": rng.integers(0, max(2, nk // 3),
                              nk).astype(np.float32),
            "v": rng.standard_normal(nk).astype(np.float32),
        }
        kinds = ["fill", "subfill", "xform", "foreach", "reduce",
                 "dot", "scan", "hist", "topk", "join", "groupby",
                 "uniq"]
        if P > 1:
            kinds.append("rdx")
        ops = []
        for _ in range(int(rng.integers(3, 8))):
            ops.append((str(rng.choice(kinds)),
                        float(np.round(rng.standard_normal(), 3)),
                        int(rng.integers(0, n + 1)),
                        int(rng.integers(0, n + 1))))
        disable = str(rng.choice(pass_names)) \
            if rng.integers(0, 3) == 0 else None
        shrink = bool(P > 1 and rng.integers(0, 5) == 0)
        tag = f"seed={seed} it={it} P={P} n={n} nk={nk} " \
              f"disable={disable} shrink={shrink} ops={ops}"

        def rand_dist():
            cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
            bounds = np.concatenate(([0], cuts, [n]))
            return tuple(int(y - x)
                         for x, y in zip(bounds[:-1], bounds[1:]))

        dists = [rand_dist() if P > 1 else None for _ in range(4)]

        def run(mode, inject):
            """One full chain under DR_TPU_PLAN_OPT=mode on fresh
            containers; returns (container arrays, scalar floats,
            relational results)."""
            tuning.clear_session()
            conts = {nm: dr_tpu.distributed_vector.from_array(s)
                     for nm, s in srcs.items()}
            hb = dr_tpu.distributed_vector(8, np.int32)
            kk = min(5, nk)
            tv = dr_tpu.distributed_vector(kk, np.float32)
            ti = dr_tpu.distributed_vector(kk, np.int32)
            scal, autos, di = [], [], 0
            with env_override(DR_TPU_PLAN_OPT=mode,
                              DR_TPU_PLAN_OPT_DISABLE=disable,
                              DR_TPU_ELASTIC="1" if inject else None):
                if inject:
                    # the §16 fate matrix: data on the lost rank only
                    # RESTORES from a checkpoint — the arm audits the
                    # optimizer's replay, not the rescue matrix
                    every = dict(conts, hb=hb, tv=tv, ti=ti)
                    for nm, v in every.items():
                        dr_tpu.checkpoint.save(
                            str(tmp_path / f"po_{it}_{nm}.npz"), v)
                with dr_tpu.deferred():
                    if inject:
                        faults.inject("device.lost", "device_lost",
                                      times=1)
                    for kind, c, i0, i1 in ops:
                        a, b = conts["a"], conts["b"]
                        if kind == "fill":
                            dr_tpu.fill(a, c)
                        elif kind == "subfill":
                            lo, hi = min(i0, i1), max(i0, i1)
                            dr_tpu.fill(b[lo:hi], c)
                        elif kind == "xform":
                            dr_tpu.transform(a, b, _po_shift, c)
                        elif kind == "foreach":
                            dr_tpu.for_each(a, _po_scale, c)
                        elif kind == "reduce":
                            scal.append(dr_tpu.reduce(b))
                        elif kind == "dot":
                            scal.append(dr_tpu.dot(a, b))
                        elif kind == "scan":
                            dr_tpu.inclusive_scan(a, b)
                        elif kind == "hist":
                            dr_tpu.histogram(a, hb, -4.0, 4.0)
                        elif kind == "topk":
                            dr_tpu.top_k(a, tv, ti)
                        elif kind == "rdx":
                            # an explicit-sizes dist cannot replay
                            # onto a shrunken mesh (SPEC §18.3): the
                            # shrink arm re-targets the default layout
                            dr_tpu.redistribute(
                                conts["a"], None if inject
                                else dists[di % len(dists)])
                            di += 1
                        elif kind == "join":
                            autos.append(dr_tpu.join_auto(
                                conts["k"], conts["v"], conts["k"],
                                conts["v"]))
                        elif kind == "groupby":
                            autos.append(dr_tpu.groupby_auto(
                                conts["k"], conts["v"], agg="sum"))
                        else:  # uniq
                            autos.append(
                                dr_tpu.unique_auto(conts["k"]))
                out_c = {nm: dr_tpu.to_numpy(v)
                         for nm, v in conts.items()}
                out_c["hb"] = dr_tpu.to_numpy(hb)
                out_c["tv"] = dr_tpu.to_numpy(tv)
                out_c["ti"] = dr_tpu.to_numpy(ti)
                out_s = [float(s) for s in scal]
                out_r = [(r.count, [np.asarray(x)
                                    for x in r.arrays()])
                         for r in autos]
            return out_c, out_s, out_r

        try:
            base_c, base_s, base_r = run("0", inject=False)
            got_c, got_s, got_r = run("all", inject=shrink)
        finally:
            faults.clear()
        if shrink:
            # the rescue shrank the session: restore the full mesh
            # for the next iteration (conftest restores post-test)
            from dr_tpu.utils import elastic
            elastic.reset()

        def cmp(b, g, msg):
            # the one carve-out: a shrink changes the MESH WIDTH, so
            # float collectives (psum trees, scan carries) regroup —
            # cross-width FP identity is impossible; the elastic
            # suite's tolerance applies.  Unshrunk chains stay EXACT.
            b, g = np.asarray(b), np.asarray(g)
            if shrink and b.dtype.kind == "f":
                np.testing.assert_allclose(b, g, rtol=1e-5,
                                           atol=1e-6, err_msg=msg)
            else:
                np.testing.assert_array_equal(b, g, err_msg=msg)

        for nm in base_c:
            cmp(base_c[nm], got_c[nm], f"{tag}: {nm}")
        assert len(base_s) == len(got_s), tag
        for bs, gs in zip(base_s, got_s):
            cmp(np.float64(bs), np.float64(gs), f"{tag}: scalar")
        assert len(base_r) == len(got_r), tag
        for (bm, barrs), (gm, garrs) in zip(base_r, got_r):
            assert bm == gm, f"{tag}: relational count {bm} != {gm}"
            for ba, ga in zip(barrs, garrs):
                cmp(ba, ga, tag)


# ---------------------------------------------------------------------------
# plansan: armed shadow-verifier + serializability oracle (SPEC §23)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_plansan(seed):
    """§23 PLANSAN arm (tools/fuzz_crank.sh): seeded random recorded
    chains — fusible fills / transforms / reduce / dot / histogram /
    top_k / redistribute, the opaque scan, and the relational auto op
    (born-container exemption coverage) — flushed ARMED (the
    within-process equivalent of ``DR_TPU_SANITIZE=1``): the shadow
    verifier abstractly replays every fused run against its declared
    footprint, the container watcher wraps every opaque thunk, and the
    conflict-serializability oracle proves each optimized queue
    conflict-equivalent to its recorded order — under a RANDOM pass
    subset via ``DR_TPU_PLAN_OPT_DISABLE`` so every §21 pass
    combination faces the oracle, not just all-on/all-off.  Green
    means honest record sites never classify; the other direction
    (each family's seeded under-declaration CAUGHT) is the
    tests/test_plansan.py mutation battery.  An unarmed control run on
    identical inputs pins bit-identity: plansan is observation-only."""
    import jax

    from dr_tpu import tuning
    from dr_tpu.plan import opt as plan_opt
    from dr_tpu.utils import sanitize, spmd_guard

    rng = np.random.default_rng(2300 + seed)
    iters = ITERS if env_raw("DR_TPU_FUZZ_ITERS") is not None \
        else ITERS // 2
    pass_names = plan_opt.PASS_NAMES
    for it in range(max(3, iters // 8)):
        P = min(int(rng.integers(1, 9)), len(jax.devices()))
        dr_tpu.init(jax.devices()[:P])
        n = int(rng.integers(8, 65))
        nk = int(rng.integers(4, 33))
        srcs = {
            "a": rng.standard_normal(n).astype(np.float32),
            "b": rng.standard_normal(n).astype(np.float32),
            "k": rng.integers(0, max(2, nk // 3),
                              nk).astype(np.float32),
        }
        kinds = ["fill", "subfill", "xform", "foreach", "reduce",
                 "dot", "scan", "hist", "topk", "uniq"]
        if P > 1:
            kinds.append("rdx")
        ops = []
        for _ in range(int(rng.integers(3, 8))):
            ops.append((str(rng.choice(kinds)),
                        float(np.round(rng.standard_normal(), 3)),
                        int(rng.integers(0, n + 1)),
                        int(rng.integers(0, n + 1))))
        # a random SUBSET of passes disabled — the oracle must hold
        # for every pass combination, not just the bisection pairs
        sub = [p for p in pass_names if rng.integers(0, 2) == 0]
        disable = ",".join(sub) if sub else None
        tag = f"seed={seed} it={it} P={P} n={n} nk={nk} " \
              f"disable={disable} ops={ops}"

        def rand_dist():
            cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
            bounds = np.concatenate(([0], cuts, [n]))
            return tuple(int(y - x)
                         for x, y in zip(bounds[:-1], bounds[1:]))

        dists = [rand_dist() if P > 1 else None for _ in range(4)]

        def run(armed):
            """One full chain on fresh containers, the plansan layer
            armed or not; returns (container arrays, scalars,
            relational results)."""
            tuning.clear_session()
            conts = {nm: dr_tpu.distributed_vector.from_array(s)
                     for nm, s in srcs.items()}
            hb = dr_tpu.distributed_vector(8, np.int32)
            kk = min(5, n)
            tv = dr_tpu.distributed_vector(kk, np.float32)
            ti = dr_tpu.distributed_vector(kk, np.int32)
            scal, autos, di = [], [], 0
            prev = (sanitize._installed, spmd_guard._compile_hook,
                    spmd_guard._canon_check_hook)
            if armed:
                spmd_guard._compile_hook = sanitize._on_compile
                spmd_guard._canon_check_hook = sanitize._on_record
                sanitize._installed = True
                sanitize.reset_epoch()
            try:
                with env_override(DR_TPU_PLAN_OPT="all",
                                  DR_TPU_PLAN_OPT_DISABLE=disable):
                    with dr_tpu.deferred():
                        for kind, c, i0, i1 in ops:
                            a, b = conts["a"], conts["b"]
                            if kind == "fill":
                                dr_tpu.fill(a, c)
                            elif kind == "subfill":
                                lo, hi = min(i0, i1), max(i0, i1)
                                dr_tpu.fill(b[lo:hi], c)
                            elif kind == "xform":
                                dr_tpu.transform(a, b, _po_shift, c)
                            elif kind == "foreach":
                                dr_tpu.for_each(a, _po_scale, c)
                            elif kind == "reduce":
                                scal.append(dr_tpu.reduce(b))
                            elif kind == "dot":
                                scal.append(dr_tpu.dot(a, b))
                            elif kind == "scan":
                                dr_tpu.inclusive_scan(a, b)
                            elif kind == "hist":
                                dr_tpu.histogram(a, hb, -4.0, 4.0)
                            elif kind == "topk":
                                dr_tpu.top_k(a, tv, ti)
                            elif kind == "rdx":
                                dr_tpu.redistribute(
                                    conts["a"],
                                    dists[di % len(dists)])
                                di += 1
                            else:  # uniq
                                autos.append(
                                    dr_tpu.unique_auto(conts["k"]))
                    out_c = {nm: dr_tpu.to_numpy(v)
                             for nm, v in conts.items()}
                    out_c["hb"] = dr_tpu.to_numpy(hb)
                    out_c["tv"] = dr_tpu.to_numpy(tv)
                    out_c["ti"] = dr_tpu.to_numpy(ti)
                    out_s = [float(s) for s in scal]
                    out_r = [(r.count, [np.asarray(x)
                                        for x in r.arrays()])
                             for r in autos]
            finally:
                (sanitize._installed, spmd_guard._compile_hook,
                 spmd_guard._canon_check_hook) = prev
            return out_c, out_s, out_r

        base_c, base_s, base_r = run(armed=False)
        got_c, got_s, got_r = run(armed=True)
        for nm in base_c:
            np.testing.assert_array_equal(
                base_c[nm], got_c[nm], err_msg=f"{tag}: {nm}")
        assert base_s == got_s, f"{tag}: scalars"
        assert len(base_r) == len(got_r), tag
        for (bm, barrs), (gm, garrs) in zip(base_r, got_r):
            assert bm == gm, f"{tag}: relational count {bm} != {gm}"
            for ba, ga in zip(barrs, garrs):
                np.testing.assert_array_equal(ba, ga, err_msg=tag)


# ---------------------------------------------------------------------------
# On-chip kernel tier (docs/SPEC.md §22): pallas-vs-xla arm parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_fuzz_kernel_parity(seed, tmp_path):
    """§22 KERNEL arm (tools/fuzz_crank.sh): every registered kernel
    arm (``ops/kernels.ARM_NAMES``) runs PALLAS-pinned — interpret mode
    on this CPU mesh, the §22.3 contract — and XLA-pinned on identical
    inputs, compared BIT-for-bit: sort keys / key+payload / descending
    across dtypes (NaNs included), the groupby aggs, histogram, and the
    kernel-eligible reduce monoids.  The scan arm's kernel accumulates
    in f32 under a different association than the matmul-cumsum, so
    §22.4 scopes it to tolerance, not bits.  A slice of iterations
    additionally injects a mid-sort device loss under DR_TPU_ELASTIC=1
    on the pallas arm: the §16 shrink-and-rescue must land exactly the
    xla no-fault values (integer keys keep the comparison exact across
    the mesh-width change)."""
    import jax

    from dr_tpu import faults, tuning
    from dr_tpu.ops import kernels
    from dr_tpu.utils import elastic, resilience

    # the registry is the single source of arm truth: pin EVERY arm's
    # env at once so a seam quietly rerouted to a new arm stays covered
    assert set(kernels.ARM_NAMES) >= {"sort_local", "segred", "hist",
                                      "scan"}
    pin_vars = [env for _, env, _, _, _ in kernels.ARMS]

    rng = np.random.default_rng(2200 + seed)
    cranked = env_raw("DR_TPU_FUZZ_ITERS") is not None
    # geometries come from small quantized menus: arm parity is the
    # property under test, not geometry fuzzing (sort_family cranks
    # that) — quantizing lets the program cache absorb repeat shapes.
    # CI runs ONE iteration per seed: every pallas-vs-xla program pair
    # compiles fresh geometry, the tier-1 budget's scarcest resource —
    # depth soaks live with the crank (tools/fuzz_crank.sh KERNEL arm)
    for it in range(max(6, ITERS // 4) if cranked else 1):
        P = min(int(rng.integers(1, 5)), len(jax.devices()))
        dr_tpu.init(jax.devices()[:P])
        n = int(rng.choice((32, 96, 144, 176)))
        nk = int(rng.choice((16, 33, 48)))
        bins = int(rng.choice((4, 8, 13)))
        desc = bool(rng.integers(0, 2))
        kkind = int(rng.integers(0, 3))
        if kkind == 0:
            ksrc = rng.standard_normal(n).astype(np.float32)
            if rng.integers(0, 4) == 0:
                ksrc[rng.integers(0, n, size=max(1, n // 8))] = np.nan
        elif kkind == 1:
            ksrc = rng.integers(0, 5, n).astype(np.float32)  # ties
        else:
            ksrc = rng.integers(-40, 40, n).astype(np.int32)
        pay = np.arange(n, dtype=np.int32)
        gk = rng.integers(0, max(2, nk // 3), nk).astype(
            np.float32 if rng.integers(0, 2) else np.int32)
        gv = rng.standard_normal(nk).astype(np.float32)
        agg = str(rng.choice(["sum", "min", "max", "count", "mean"]))
        hsrc = rng.standard_normal(n).astype(np.float32)
        ri = rng.integers(-9, 9, n).astype(np.int32)
        rop = [None, min, max][int(rng.integers(0, 3))]
        shrink = bool(P > 1 and rng.integers(0, 4) == 0)
        tag = f"seed={seed} it={it} P={P} n={n} nk={nk} bins={bins} " \
              f"desc={desc} kkind={kkind} agg={agg} shrink={shrink}"

        def run(mode, inject):
            tuning.clear_session()
            out = {}
            with env_override(
                    DR_TPU_ELASTIC="1" if inject else None,
                    **{v: mode for v in pin_vars}):
                v = dr_tpu.distributed_vector.from_array(ksrc)
                if inject:
                    dr_tpu.checkpoint.save(
                        str(tmp_path / f"kp_{it}.npz"), v)
                    with faults.injected("device.lost", "device_lost",
                                         times=1) as sp:
                        resilience.retry(
                            lambda: dr_tpu.sort(v, descending=desc),
                            attempts=2, sleep=lambda s: None)
                        assert sp.fired == 1, tag
                else:
                    dr_tpu.sort(v, descending=desc)
                out["sort"] = dr_tpu.to_numpy(v)
                kd = dr_tpu.distributed_vector.from_array(ksrc)
                vd = dr_tpu.distributed_vector.from_array(pay)
                dr_tpu.sort_by_key(kd, vd, descending=desc)
                out["kv_k"] = dr_tpu.to_numpy(kd)
                out["kv_v"] = dr_tpu.to_numpy(vd)
                gkd = dr_tpu.distributed_vector.from_array(gk)
                gvd = dr_tpu.distributed_vector.from_array(gv)
                ok = dr_tpu.distributed_vector(nk, gk.dtype)
                ov = dr_tpu.distributed_vector(
                    nk, np.int32 if agg == "count" else np.float32)
                ng = dr_tpu.groupby_aggregate(
                    gkd, None if agg == "count" else gvd, ok, ov,
                    agg=agg)
                out["gb_n"] = np.int64(int(ng))
                out["gb_k"] = dr_tpu.to_numpy(ok)
                out["gb_v"] = dr_tpu.to_numpy(ov)
                hv = dr_tpu.distributed_vector.from_array(hsrc)
                hb = dr_tpu.distributed_vector(bins, np.int32)
                dr_tpu.histogram(hv, hb, -3.0, 3.0)
                out["hist"] = dr_tpu.to_numpy(hb)
                rv = dr_tpu.distributed_vector.from_array(ri)
                out["red"] = np.asarray(dr_tpu.reduce(rv, op=rop))
            return out

        try:
            base = run("xla", inject=False)
            got = run("pallas", inject=shrink)
        finally:
            faults.clear()
        if shrink:
            elastic.reset()
            dr_tpu.init(jax.devices()[:P])
        for nm in base:
            b, g = np.asarray(base[nm]), np.asarray(got[nm])
            if shrink and b.dtype.kind == "f" and nm.startswith("gb"):
                # a shrink changes the MESH WIDTH: the groupby float
                # aggregate's psum tree regroups (the §21.3/§16
                # carve-out) — everything else stays EXACT (sorts are
                # permutations; int channels are associative)
                np.testing.assert_allclose(b, g, rtol=1e-5, atol=1e-6,
                                           err_msg=f"{tag}: {nm}")
            else:
                np.testing.assert_array_equal(b, g,
                                              err_msg=f"{tag}: {nm}")

    # the scan arm once per battery (its minimal eligible geometry is
    # 128*128 per shard — pick_chunk needs rows % 128 == 0 — so the
    # interpret trace is the costliest leg; tier-1 already exercises
    # the interpret scan kernel via test_scan's
    # test_distributed_scan_with_kernel_interpret): tolerance, not
    # bits — §22.4
    if seed != 0 or not cranked:
        return
    P = min(2, len(jax.devices()))
    dr_tpu.init(jax.devices()[:P])
    ns = 128 * 128 * P - max(P - 1, 0)
    src = rng.standard_normal(ns).astype(np.float32)

    def run_scan(mode):
        tuning.clear_session()
        with env_override(DR_TPU_SCAN_IMPL=mode):
            a = dr_tpu.distributed_vector.from_array(src)
            o = dr_tpu.distributed_vector(ns)
            dr_tpu.inclusive_scan(a, o)
            e = dr_tpu.distributed_vector(ns)
            dr_tpu.exclusive_scan(a, e)
            return dr_tpu.to_numpy(o), dr_tpu.to_numpy(e)

    bi, be = run_scan("xla")
    gi, ge = run_scan("pallas")
    np.testing.assert_allclose(bi, gi, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(be, ge, rtol=1e-4, atol=1e-3)


@pytest.mark.kernel_interpret
def test_fuzz_kernel_parity_deep():
    """Crank-depth slice of the §22 parity battery (kernel_interpret →
    slow; tools/fuzz_crank.sh KERNEL arm): per-shard geometries big
    enough to pad past one bitonic stage boundary (M > 256) and a
    groupby whose group count crosses the segred kernel's 128-lane
    tile boundary — the unrolled interpret-mode network traces too
    slowly for tier-1, which is exactly why the marker exists."""
    import jax

    from dr_tpu import tuning
    from dr_tpu.ops import kernels

    pin_vars = [env for _, env, _, _, _ in kernels.ARMS]
    P = min(2, len(jax.devices()))
    dr_tpu.init(jax.devices()[:P])
    rng = np.random.default_rng(97)
    n = 1024 * P + 7          # pads to a 2048-wide bitonic network
    nseg = 300                # > 2 segred tiles
    ksrc = rng.standard_normal(n).astype(np.float32)
    pay = np.arange(n, dtype=np.int32)
    gk = rng.integers(0, 290, 4 * nseg).astype(np.int32)
    gv = rng.standard_normal(4 * nseg).astype(np.float32)
    hsrc = rng.standard_normal(n).astype(np.float32)

    def run(mode):
        tuning.clear_session()
        out = {}
        with env_override(**{v: mode for v in pin_vars}):
            kd = dr_tpu.distributed_vector.from_array(ksrc)
            vd = dr_tpu.distributed_vector.from_array(pay)
            dr_tpu.sort_by_key(kd, vd, descending=True)
            out["kv_k"] = dr_tpu.to_numpy(kd)
            out["kv_v"] = dr_tpu.to_numpy(vd)
            gkd = dr_tpu.distributed_vector.from_array(gk)
            gvd = dr_tpu.distributed_vector.from_array(gv)
            ok = dr_tpu.distributed_vector(nseg, np.int32)
            ov = dr_tpu.distributed_vector(nseg, np.float32)
            ng = dr_tpu.groupby_aggregate(gkd, gvd, ok, ov, agg="min")
            out["gb_n"] = np.int64(int(ng))
            out["gb_k"] = dr_tpu.to_numpy(ok)
            out["gb_v"] = dr_tpu.to_numpy(ov)
            hv = dr_tpu.distributed_vector.from_array(hsrc)
            hb = dr_tpu.distributed_vector(257, np.int32)
            dr_tpu.histogram(hv, hb, -3.0, 3.0)
            out["hist"] = dr_tpu.to_numpy(hb)
        return out

    base = run("xla")
    got = run("pallas")
    for nm in base:
        np.testing.assert_array_equal(base[nm], got[nm], err_msg=nm)
