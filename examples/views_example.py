#!/usr/bin/env python
"""Views tour: take/drop/slice/zip/transform/enumerate over distributed
vectors (reference examples/shp/{zip,take}*.cpp, examples/mhp/views).
"""

import sys

import numpy as np


def main():
    import dr_tpu
    from dr_tpu import views

    dr_tpu.init()
    n = 1 << 10
    a = dr_tpu.distributed_vector(n)
    b = dr_tpu.distributed_vector(n)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 1.0)

    taken = a | views.take(100)
    assert len(taken) == 100

    sl = a | views.slice_view((10, 20))
    np.testing.assert_array_equal(dr_tpu.to_numpy(sl),
                                  np.arange(10, 20, dtype=np.float32))

    doubled = a | views.transform(lambda x: 2 * x)
    assert dr_tpu.reduce(doubled) == float(np.arange(n, dtype=np.float64)
                                           .sum() * 2)

    z = views.zip_view(a, b)
    assert dr_tpu.aligned(a, b)
    c = dr_tpu.distributed_vector(n)
    dr_tpu.transform(z, c, lambda x, y: x + y)
    np.testing.assert_array_equal(dr_tpu.to_numpy(c),
                                  np.arange(n, dtype=np.float32) + 1)

    first = list(views.enumerate_view(a | views.take(3)))
    assert first == [(0, 0.0), (1, 1.0), (2, 2.0)]

    dr_tpu.print_range(a | views.take(8), "a[:8]")
    print("views example: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
