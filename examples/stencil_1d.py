#!/usr/bin/env python
"""1-D stencil example with halo exchange per step.

TPU re-design of the reference example ``examples/mhp/stencil-1d.cpp``:
same workload (iterated 3-point mean over a distributed vector, halo
exchange per step, serial-oracle check), but the exchange+transform pair is
one fused XLA program per step and all steps run device-side.

Usage: python examples/stencil_1d.py [-n SIZE] [-s STEPS] [--cpu N]
"""

import argparse
import sys

import numpy as np


def serial(x, steps):
    x = x.astype(np.float64).copy()
    for _ in range(steps):
        y = x.copy()
        y[1:-1] = (x[:-2] + x[1:-1] + x[2:]) / 3
        x = y
    return x


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 20)
    ap.add_argument("-s", "--steps", type=int, default=10)
    ap.add_argument("--cpu", type=int, default=0, metavar="N",
                    help="run on a virtual N-device CPU mesh")
    args = ap.parse_args()

    if args.cpu:
        import os
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.cpu}")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import dr_tpu

    dr_tpu.init()
    src = np.random.default_rng(0).standard_normal(args.n).astype(np.float32)
    hb = dr_tpu.halo_bounds(1, 1)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)

    out = dr_tpu.stencil_iterate(a, b, [1 / 3, 1 / 3, 1 / 3],
                                 steps=args.steps)

    got = dr_tpu.to_numpy(out)
    ref = serial(src, args.steps)
    ok = np.allclose(got, ref, rtol=1e-3, atol=1e-5)
    print(f"n={args.n} steps={args.steps} nprocs={dr_tpu.nprocs()} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
