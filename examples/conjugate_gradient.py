#!/usr/bin/env python
"""Conjugate gradients on a distributed sparse system: solve A x = b.

A full composition of the framework's algorithm surface in one loop —
``gemv`` (SpMV over row tiles), ``dot`` (fused transform_reduce), and
``transform`` (axpy updates) on block-distributed vectors — the natural
"what distributed-ranges is for" workload (the reference demonstrates
the pieces separately: examples/shp/gemv_example.cpp,
examples/shp/dot_product.cpp, examples/mhp/vector-add.cpp; CG is their
composition).

A is the 1-D Laplacian (tridiagonal [-1, 2, -1] plus identity shift):
symmetric positive definite, so CG converges; the banded structure
takes the BCSR dense-tile MXU path on TPU.
"""

import argparse
import sys

import numpy as np


def build_laplacian(n: int):
    """COO entries of I + Laplacian_1d (SPD, condition ~n^2/pi^2)."""
    ii = np.concatenate([np.arange(n), np.arange(n - 1), np.arange(1, n)])
    jj = np.concatenate([np.arange(n), np.arange(1, n), np.arange(n - 1)])
    vv = np.concatenate([
        np.full(n, 3.0), np.full(n - 1, -1.0), np.full(n - 1, -1.0),
    ]).astype(np.float32)
    return ii, jj, vv


def _axpy(x, p, alpha):
    return x + alpha * p


def _axmy(r, ap, alpha):
    return r - alpha * ap


# Module-level ops + transform's trailing scalar arguments: the
# coefficients are TRACED, so all iterations share ONE compiled program
# per update.  (Closing over alpha/beta in per-iteration lambdas would
# compile — and pin — a fresh program every iteration: the op identity
# keys the program cache.)
def cg(A, b, iters: int, tol: float = 1e-6):
    """Textbook CG over the distributed containers; returns (x, resid)."""
    import dr_tpu

    n = len(b)
    x = dr_tpu.distributed_vector(n, np.float32)
    r = dr_tpu.distributed_vector(n, np.float32)
    p = dr_tpu.distributed_vector(n, np.float32)
    Ap = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(x, 0.0)
    dr_tpu.copy(b, r)          # r = b - A @ 0 = b
    dr_tpu.copy(b, p)
    rs = float(dr_tpu.dot(r, r))
    for it in range(iters):
        dr_tpu.fill(Ap, 0.0)
        dr_tpu.gemv(Ap, A, p)  # gemv ACCUMULATES (c += A·b), hence the fill
        alpha = rs / float(dr_tpu.dot(p, Ap))
        # x += alpha p ; r -= alpha Ap   (fused zip|transform programs)
        dr_tpu.transform(dr_tpu.views.zip(x, p), x, _axpy, alpha)
        dr_tpu.transform(dr_tpu.views.zip(r, Ap), r, _axmy, alpha)
        rs_new = float(dr_tpu.dot(r, r))
        if rs_new < tol * tol:
            return x, np.sqrt(rs_new), it + 1
        beta = rs_new / rs
        dr_tpu.transform(dr_tpu.views.zip(r, p), p, _axpy, beta)
        rs = rs_new
    return x, np.sqrt(rs), iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 12)
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    n = args.n
    ii, jj, vv = build_laplacian(n)
    A = dr_tpu.sparse_matrix.from_coo((n, n), ii, jj, vv)
    rng = np.random.default_rng(0)
    b = rng.standard_normal(n).astype(np.float32)

    x, resid, its = cg(A, b, args.iters)

    # oracle: dense solve
    Ad = np.zeros((n, n), dtype=np.float64)
    Ad[ii, jj] = vv
    ref = np.linalg.solve(Ad, b.astype(np.float64))
    err = np.abs(dr_tpu.to_numpy(x) - ref).max()
    print(f"n={n} iters={its} resid={resid:.3e} max_err={err:.3e}")
    ok = resid < 1e-3 and err < 1e-2
    print("CG", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
