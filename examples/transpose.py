#!/usr/bin/env python
"""Distributed matrix transpose over an N-D mdarray.

The reference wrote this example against its *planned* mdspan surface and
never built it (``examples/mhp/transpose-cpu.cpp:27-54`` — absent from
the CMake lists; the per-rank loop copies local transposed blocks into
remote submdspans).  Here the whole thing is one jitted program: the
sharded transpose lowers to an XLA all-to-all over the mesh.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", type=int, default=384)
    ap.add_argument("-n", type=int, default=256)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    src = np.arange(args.m * args.n, dtype=np.float32).reshape(
        args.m, args.n)
    A = dr_tpu.distributed_mdarray.from_array(src)
    B = dr_tpu.distributed_mdarray((args.n, args.m), np.float32)
    dr_tpu.transpose(B, A)

    # the reference's check: serial transpose oracle (transpose-serial.hpp)
    got = B.materialize()
    ok = np.array_equal(got, src.T)
    print(f"m={args.m} n={args.n} grid={A.grid} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
