#!/usr/bin/env python
"""Top-k selection over a distributed vector.

Composition demo for the sort family (beyond-parity surface): score a
distributed vector, take the k largest with their original positions
via the stable key-value sort, and check against numpy.  The whole
selection is collective — no host-side gather of the full data.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 18)
    ap.add_argument("-k", type=int, default=8)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    rng = np.random.default_rng(0)
    scores = rng.standard_normal(args.n).astype(np.float32)

    s = dr_tpu.distributed_vector.from_array(scores)
    pos = dr_tpu.distributed_vector(args.n, dtype=np.int32)
    dr_tpu.iota(pos, 0)
    # stable key-value sort, descending: ties keep ascending-order
    # positions reversed (documented semantics)
    dr_tpu.sort_by_key(s, pos, descending=True)

    top_scores = dr_tpu.to_numpy(s[0:args.k])
    top_pos = dr_tpu.to_numpy(pos[0:args.k])

    order = np.argsort(scores, kind="stable")[::-1][:args.k]
    ok = (np.array_equal(top_scores, scores[order])
          and np.array_equal(top_pos, order))
    print(f"n={args.n} k={args.k} nprocs={dr_tpu.nprocs()} "
          f"best={top_scores[0]:.4f}@{top_pos[0]} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
