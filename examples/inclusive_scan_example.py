#!/usr/bin/env python
"""Distributed inclusive scan (prefix sum).

Analog of ``examples/shp/inclusive_scan_example.cpp``: the reference's
3-phase multi-GPU scan is one shard_map program here (local scan +
all_gather carry exchange + fixup).
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 20)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    src = np.random.default_rng(0).integers(0, 100, args.n)\
        .astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(args.n)
    dr_tpu.inclusive_scan(a, out)

    got = dr_tpu.to_numpy(out)
    ref = np.cumsum(src, dtype=np.float32)
    ok = np.allclose(got, ref, rtol=1e-3)
    print(f"n={args.n} nprocs={dr_tpu.nprocs()} total={got[-1]:.0f} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
