#!/usr/bin/env python
"""Vector add: c = a + b over distributed vectors.

Analog of the reference examples ``examples/mhp/vector-add.cpp`` /
``examples/shp/vector_example.cpp`` — zip | transform on aligned vectors
runs shard-local with zero communication.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 20)
    args = ap.parse_args()

    import dr_tpu
    from dr_tpu import views

    dr_tpu.init()
    a = dr_tpu.distributed_vector(args.n)
    b = dr_tpu.distributed_vector(args.n)
    c = dr_tpu.distributed_vector(args.n)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 10.0)
    dr_tpu.transform(views.zip_view(a, b), c, lambda x, y: x + y)

    got = dr_tpu.to_numpy(c)
    ref = np.arange(args.n, dtype=np.float32) + 10.0
    ok = np.allclose(got, ref)
    print(f"n={args.n} nprocs={dr_tpu.nprocs()} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
