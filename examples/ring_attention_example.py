"""Sequence-parallel ring attention over the mesh ring.

The long-context capability (SURVEY.md §5): Q/K/V shard over the
sequence axis, K/V blocks rotate with lax.ppermute (the same ring as the
halo subsystem), and an online softmax merges blocks — O(block) memory
for any total sequence length.  Validated against dense single-device
attention.

Run: python examples/ring_attention_example.py [--seq 512] [--heads 4]
"""

import argparse

import numpy as np

import dr_tpu


def dense_reference(q, k, v, causal):
    B, S, h, d = q.shape
    qt = np.moveaxis(q, 2, 1).astype(np.float64)   # (B,h,S,d)
    kt = np.moveaxis(k, 2, 1).astype(np.float64)
    vt = np.moveaxis(v, 2, 1).astype(np.float64)
    logits = qt @ np.swapaxes(kt, -1, -2) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        logits = np.where(mask, logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.moveaxis(p @ vt, 1, 2)               # (B,S,h,d)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--causal", action=argparse.BooleanOptionalAction,
                    default=True)
    args = ap.parse_args()

    dr_tpu.init()
    P = dr_tpu.nprocs()
    S = args.seq // P * P
    rng = np.random.default_rng(0)
    q, k, v = (rng.standard_normal(
        (1, S, args.heads, args.head_dim)).astype(np.float32)
        for _ in range(3))

    out = np.asarray(dr_tpu.ring_attention(q, k, v, causal=args.causal))
    ref = dense_reference(q, k, v, args.causal)
    err = np.abs(out - ref).max()
    print(f"ring attention over {P} shard(s), seq={S}: "
          f"max |err| vs dense reference = {err:.2e}")
    assert err < 1e-3, "mismatch vs dense reference"

    # grouped-query attention: fewer shared K/V heads (h % hkv == 0);
    # the ring carries only the hkv heads
    if args.heads % 2 == 0:
        hkv = args.heads // 2
        kg, vg = k[:, :, :hkv], v[:, :, :hkv]
        gqa = np.asarray(dr_tpu.ring_attention(q, kg, vg,
                                               causal=args.causal))
        ref_g = dense_reference(q, np.repeat(kg, 2, axis=2),
                                np.repeat(vg, 2, axis=2), args.causal)
        err_g = np.abs(gqa - ref_g).max()
        print(f"grouped-query (hkv={hkv}): max |err| = {err_g:.2e}")
        assert err_g < 1e-3, "GQA mismatch vs dense reference"
    print("PASSED")


if __name__ == "__main__":
    main()
