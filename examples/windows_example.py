#!/usr/bin/env python
"""Subrange windows and mixed distributions — no fallback anywhere.

The reference's algorithms operate on whole aligned containers; its
misaligned shapes drop to a serial element fallback
(mhp/algorithms/cpu_algorithms.hpp:44-48).  dr_tpu runs EVERY
distributed shape as a fused shard_map program (round 5): subrange
windows, mismatched in/out windows (realigned by one static masked
all_to_all), overlapping windows of one container, uneven "team"
distributions, and even identityless custom reduction ops.

This example sorts a window in place, scans it into a differently-
offset destination window, key-value-sorts two overlapping windows of
ONE container, and folds a custom op over an uneven distribution —
then checks everything against numpy.
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 16)
    args = ap.parse_args()
    n = args.n

    import dr_tpu

    dr_tpu.init()
    P = dr_tpu.nprocs()
    rng = np.random.default_rng(0)
    src = rng.standard_normal(n).astype(np.float32)

    # 1. sort a window in place: outside cells stay untouched bit-exact
    v = dr_tpu.distributed_vector.from_array(src)
    lo, hi = n // 8, n - n // 8
    dr_tpu.sort(v[lo:hi])
    ref = src.copy()
    ref[lo:hi] = np.sort(src[lo:hi])
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), ref)

    # 2. scan the sorted window into a DIFFERENT window of another
    # container (the in/out offsets differ; the program realigns)
    out = dr_tpu.distributed_vector(n, np.float32)
    wn = hi - lo - 3
    dr_tpu.inclusive_scan(v[lo:lo + wn], out[3:3 + wn])
    got = dr_tpu.to_numpy(out)
    # f32 prefix sums of sorted data cross zero, so relative error is
    # unbounded there; accumulation-order noise grows like
    # eps32 * |prefix| * sqrt(terms) — size the absolute tolerance
    # from the oracle's own magnitude so any -n passes
    oracle = np.cumsum(ref[lo:lo + wn].astype(np.float64))
    np.testing.assert_allclose(
        got[3:3 + wn], oracle, rtol=1e-3,
        atol=np.abs(oracle).max() * 1e-5 * np.sqrt(wn))

    # 3. overlapping key/value windows of ONE container (payload-last
    # blend order, the documented contract)
    w = dr_tpu.distributed_vector.from_array(src)
    kw = n // 2
    dr_tpu.sort_by_key(w[0:kw], w[kw // 2:kw // 2 + kw])
    wref = src.copy()
    order = np.argsort(src[0:kw], kind="stable")
    wref[0:kw] = src[0:kw][order]
    wref[kw // 2:kw // 2 + kw] = src[kw // 2:kw // 2 + kw][order]
    np.testing.assert_array_equal(dr_tpu.to_numpy(w), wref)

    # 4. identityless custom reduce over an uneven distribution (with
    # empty "team" shards when the mesh has more than one device)
    if P == 1:
        sizes = [n]
    else:
        sizes = [0] * P
        sizes[0] = n // 2
        sizes[-1] = n - n // 2
    pos = np.abs(src) * 0.001 + 0.999
    u = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    u.assign_array(pos)
    # fold over a bounded WINDOW of the uneven container: a product of
    # arbitrarily many near-1 factors would drift out of f32 range,
    # and the window exercises the same fused program
    m = min(n, 8192)
    got_r = dr_tpu.reduce(u[0:m], op=lambda a, b: a * b * 1.0)
    np.testing.assert_allclose(
        got_r, float(np.prod(pos[:m].astype(np.float64))), rtol=1e-3)

    print(f"windows example OK: n={n} P={P} "
          f"(window sort + realigned scan + overlap kv + uneven "
          f"custom reduce)")


if __name__ == "__main__":
    main()
