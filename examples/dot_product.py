#!/usr/bin/env python
"""Dot product: zip | transform | reduce — the reference's headline
transform_reduce workload (``examples/shp/dot_product.cpp:11-18``).

The whole pipeline fuses into one masked sharded reduction program; the
cross-shard combine is XLA's all-reduce over ICI.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 20)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal(args.n).astype(np.float32)
    y = rng.standard_normal(args.n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(x)
    b = dr_tpu.distributed_vector.from_array(y)

    got = dr_tpu.dot(a, b)
    ref = float(np.dot(x.astype(np.float64), y.astype(np.float64)))
    ok = abs(got - ref) <= 1e-3 * max(1.0, abs(ref))
    print(f"n={args.n} nprocs={dr_tpu.nprocs()} dot={got:.4f} "
          f"ref={ref:.4f} check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
