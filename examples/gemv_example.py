#!/usr/bin/env python
"""Sparse SpMV: c = A·b for a random CSR matrix.

Analog of ``examples/shp/gemv_example.cpp:18-41``: random sparse A
row-tiled over the mesh, b broadcast to every shard, per-tile contraction.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", type=int, default=1 << 12)
    ap.add_argument("-n", type=int, default=1 << 12)
    ap.add_argument("--density", type=float, default=0.01)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    sp = dr_tpu.random_sparse_matrix((args.m, args.n), args.density, seed=0)
    b = np.ones(args.n, dtype=np.float32)
    c = dr_tpu.distributed_vector(args.m)
    dr_tpu.gemv(c, sp, b)

    ref = sp.to_dense() @ b
    ok = np.allclose(dr_tpu.to_numpy(c), ref, rtol=1e-3, atol=1e-4)
    print(f"m={args.m} n={args.n} nnz={sp.nnz} nprocs={dr_tpu.nprocs()} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
