#!/usr/bin/env python
"""Sparse SpMV: c = A·b for a random CSR matrix.

Analog of ``examples/shp/gemv_example.cpp:18-41``: random sparse A
row-tiled over the mesh, b broadcast to every shard, per-tile contraction.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", type=int, default=1 << 12)
    ap.add_argument("-n", type=int, default=1 << 12)
    ap.add_argument("--density", type=float, default=0.01)
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    sp = dr_tpu.random_sparse_matrix((args.m, args.n), args.density, seed=0)
    b = np.ones(args.n, dtype=np.float32)
    c = dr_tpu.distributed_vector(args.m)
    dr_tpu.gemv(c, sp, b)

    ref = sp.to_dense() @ b
    ok = np.allclose(dr_tpu.to_numpy(c), ref, rtol=1e-3, atol=1e-4)

    # block-banded matrix: the BCSR dense-tile MXU path (one 128-slice
    # gather per (8, 128) tile instead of one per nnz)
    m2 = max(64, args.m - args.m % 8)
    half = 8
    ii = np.repeat(np.arange(m2), 2 * half + 1)
    jj = ii + np.tile(np.arange(-half, half + 1), m2)
    keep = (jj >= 0) & (jj < m2)
    rngv = np.random.default_rng(1)
    band = dr_tpu.sparse_matrix.from_coo(
        (m2, m2), ii[keep], jj[keep],
        rngv.standard_normal(int(keep.sum())).astype(np.float32))
    bcsr = band.ensure_bcsr()
    b2 = np.linspace(0, 1, m2).astype(np.float32)
    c2 = dr_tpu.distributed_vector(m2)
    dr_tpu.gemv(c2, band, b2)
    ok2 = np.allclose(dr_tpu.to_numpy(c2), band.to_dense() @ b2,
                      rtol=1e-3, atol=1e-4)

    print(f"m={args.m} n={args.n} nnz={sp.nnz} nprocs={dr_tpu.nprocs()} "
          f"check={'PASS' if ok else 'FAIL'} "
          f"banded(bcsr={bcsr})={'PASS' if ok2 else 'FAIL'}")
    return 0 if ok and ok2 else 1


if __name__ == "__main__":
    sys.exit(main())
