#!/usr/bin/env python
"""Distributed sample sort.

Beyond-parity example: the reference snapshot ships no sort (later
revisions of the proposal name one).  One shard_map program per layout:
local sort, regular-sample splitters over ``all_gather``, bucket
exchange + block-layout rebalance as two static-shape ``all_to_all``
collectives (``dr_tpu/algorithms/sort.py``).
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1 << 20)
    ap.add_argument("--descending", action="store_true")
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    src = np.random.default_rng(0).standard_normal(args.n)\
        .astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(v, descending=args.descending)

    got = dr_tpu.to_numpy(v)
    ref = np.sort(src)
    if args.descending:
        ref = ref[::-1]
    ok = bool(np.array_equal(got, ref))
    print(f"n={args.n} nprocs={dr_tpu.nprocs()} "
          f"first={got[0]:.4f} last={got[-1]:.4f} "
          f"check={'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
