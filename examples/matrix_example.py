#!/usr/bin/env python
"""Matrix tour: tiled dense matrix, views, gemm, transpose, N-D mdarray
(reference examples/shp/matrix_example.cpp + the planned transpose
example)."""

import sys

import numpy as np


def main():
    import dr_tpu
    from dr_tpu.containers.mdarray import distributed_mdarray, transpose

    dr_tpu.init()
    rng = np.random.default_rng(0)
    src = rng.standard_normal((64, 48)).astype(np.float32)
    A = dr_tpu.dense_matrix.from_array(src)
    print(f"grid={A.grid_shape} tile={A.tile_shape} "
          f"tiles={len(A.tiles())}")

    # tile segments cover the matrix
    total = sum((t.re - t.rb) * (t.ce - t.cb) for t in A.tiles())
    assert total == 64 * 48

    # submatrix + row/column views
    v = A[8:16, 4:12]
    np.testing.assert_array_equal(v.materialize(), src[8:16, 4:12])
    np.testing.assert_array_equal(v.row(0).materialize(), src[8, 4:12])

    # dense gemm on the mesh (MXU path)
    B = dr_tpu.dense_matrix.from_array(
        rng.standard_normal((48, 32)).astype(np.float32))
    C = dr_tpu.gemm(A, B)
    np.testing.assert_allclose(C.materialize(),
                               src @ B.materialize(), rtol=1e-4,
                               atol=1e-4)

    # N-D mdarray + distributed transpose (all-to-all under jit)
    M = distributed_mdarray.from_array(src)
    T = distributed_mdarray((48, 64), np.float32)
    transpose(T, M)
    np.testing.assert_array_equal(T.materialize(), src.T)

    dr_tpu.print_matrix(A, "A")
    print("matrix example: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
