#!/usr/bin/env python
"""Matrix tour: tiled dense matrix, views, gemm, transpose, N-D mdarray
(reference examples/shp/matrix_example.cpp + the planned transpose
example)."""

import sys

import numpy as np


def main():
    import dr_tpu
    from dr_tpu.containers.mdarray import distributed_mdarray, transpose

    dr_tpu.init()
    rng = np.random.default_rng(0)
    src = rng.standard_normal((64, 48)).astype(np.float32)
    A = dr_tpu.dense_matrix.from_array(src)
    print(f"grid={A.grid_shape} tile={A.tile_shape} "
          f"tiles={len(A.tiles())}")

    # tile segments cover the matrix
    total = sum((t.re - t.rb) * (t.ce - t.cb) for t in A.tiles())
    assert total == 64 * 48

    # submatrix + row/column views
    v = A[8:16, 4:12]
    np.testing.assert_array_equal(v.materialize(), src[8:16, 4:12])
    np.testing.assert_array_equal(v.row(0).materialize(), src[8, 4:12])

    # dense gemm on the mesh (MXU path)
    B = dr_tpu.dense_matrix.from_array(
        rng.standard_normal((48, 32)).astype(np.float32))
    C = dr_tpu.gemm(A, B)
    np.testing.assert_allclose(C.materialize(),
                               src @ B.materialize(), rtol=1e-4,
                               atol=1e-4)

    # N-D mdarray + distributed transpose (all-to-all under jit)
    M = distributed_mdarray.from_array(src)
    T = distributed_mdarray((48, 64), np.float32)
    transpose(T, M)
    np.testing.assert_array_equal(T.materialize(), src.T)

    # block-cyclic placement: explicit tile shape, tiles placed
    # round-robin over the device grid (matrix_partition.hpp:34-86);
    # the folded storage keeps it one 2-D block-sharded array
    cyc = dr_tpu.block_cyclic(tile=(8, 8),
                              grid=dr_tpu.factor(dr_tpu.nprocs()))
    Ac = dr_tpu.dense_matrix.from_array(src, cyc)
    assert not Ac.is_block
    np.testing.assert_array_equal(Ac.materialize(), src)
    Cc = dr_tpu.gemm(Ac, B)
    np.testing.assert_allclose(Cc.materialize(), C.materialize(),
                               rtol=1e-4, atol=1e-4)

    # 2-D-partitioned sparse SpMV: per-tile partials, psum over mesh
    # columns (beyond the reference's grid_shape[1]==1 limit)
    dm = np.where(rng.random((48, 48)) < 0.3,
                  rng.standard_normal((48, 48)), 0).astype(np.float32)
    sp = dr_tpu.sparse_matrix.from_dense(
        dm, partition=dr_tpu.block_cyclic(
            grid=dr_tpu.factor(dr_tpu.nprocs())))
    bvec = np.linspace(-1, 1, 48).astype(np.float32)
    cv = dr_tpu.distributed_vector(48)
    dr_tpu.gemv(cv, sp, bvec)
    np.testing.assert_allclose(dr_tpu.to_numpy(cv), dm @ bvec,
                               rtol=1e-4, atol=1e-5)

    dr_tpu.print_matrix(A, "A")
    print("matrix example: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
