#!/usr/bin/env python
"""Multi-vector SpMM: Y = A·B for a random sparse A and dense B.

Beyond-parity surface (the reference ships only the single-vector
``gemv``, ``examples/shp/gemv_example.cpp:18-41``): on TPU, random-
pattern SpMV is bound by the per-entry gather-issue rate (docs/PERF.md
roofline), so the practical high-throughput form batches ``nv``
right-hand sides — one gathered slice of B feeds every column, and
aggregate GFLOP/s scales with ``nv`` until HBM bandwidth binds.
"""

import argparse
import sys

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", type=int, default=1 << 12)
    ap.add_argument("-k", type=int, default=16, help="nnz per row")
    ap.add_argument("--nv", type=int, default=8,
                    help="right-hand sides (columns of B)")
    args = ap.parse_args()

    import dr_tpu

    dr_tpu.init()
    rng = np.random.default_rng(0)
    rows = np.repeat(np.arange(args.m), args.k)
    cols = rng.integers(0, args.m, size=args.m * args.k)
    vals = rng.standard_normal(args.m * args.k).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((args.m, args.m), rows, cols, vals)
    B = rng.standard_normal((args.m, args.nv)).astype(np.float32)

    Y = dr_tpu.spmm(A, B)

    dense = np.zeros((args.m, args.m), np.float32)
    np.add.at(dense, (rows, cols), vals)
    ok = np.allclose(np.asarray(Y), dense @ B, rtol=1e-3, atol=1e-3)
    print(f"spmm: ({args.m}x{args.m}, {args.k} nnz/row) x "
          f"({args.m}x{args.nv})  {'OK' if ok else 'MISMATCH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
