// Randomized property fuzz for the native drtpu:: layer — the analog
// of the reference's MPI-aware libFuzzer harness
// (test/fuzz/cpu/cpu-fuzz.cpp:50-64, test/fuzz/cpu/algorithms.cpp:
// 10-57): every iteration draws a random geometry (n, nprocs,
// distribution, halo bounds, subranges), runs a randomly chosen
// drtpu:: surface, and checks it against a serial std::vector oracle.
// Single-process by design — the host executor has no ranks to
// broadcast a fuzz spec to, so a seeded PRNG loop replaces the
// libFuzzer byte stream (deterministic replay: rerun with the printed
// seed).  Built with ASan+UBSan by `make -C native fuzz`.
//
// A dedicated arm fuzzes the thp::expr DSL serializer (the bridge's
// trust boundary): random expression trees must serialize to strings
// drawn ONLY from the validated grammar's alphabet, deterministically
// (equal trees -> equal strings — the op-cache-key contract).
// Usage: fuzz_native [iterations] [seed]
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <string>
#include <vector>

#include "drtpu/algorithms.hpp"
#include "drtpu/distributed_vector.hpp"
#include "drtpu/matrix.hpp"
#include "drtpu/segment_tools.hpp"
#include "drtpu/unstructured_halo.hpp"
#include "drtpu/views.hpp"
#include "drtpu/vocabulary.hpp"
#include "../bridge/thp_bridge.hpp"  // thp::expr only; Python never inits

namespace {

int failures = 0;

// xorshift64*: deterministic across platforms (std::mt19937 would do,
// but an explicit generator keeps replay byte-stable forever)
struct Rng {
  std::uint64_t s;
  explicit Rng(std::uint64_t seed) : s(seed ? seed : 0x9E3779B97F4A7C15ULL) {}
  std::uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1DULL;
  }
  // uniform in [0, m)
  std::size_t pick(std::size_t m) { return m ? next() % m : 0; }
  double val() {  // smallish integers: exact in double, easy oracles
    return (double)(int)(next() % 41) - 20.0;
  }
};

void fail_at(const char* arm, std::uint64_t seed, int iter,
             const char* what) {
  std::printf("FUZZ FAIL arm=%s iter=%d seed=%llu: %s\n", arm, iter,
              (unsigned long long)seed, what);
  ++failures;
}

bool close(double a, double b) {
  double scale = std::abs(b) > 1.0 ? std::abs(b) : 1.0;
  return std::abs(a - b) <= 1e-9 * scale;
}

// random geometry: n, nprocs, maybe an uneven distribution
struct Geom {
  std::size_t n, p;
  bool uneven;
  std::vector<std::size_t> sizes;
};

Geom draw_geom(Rng& rng, std::size_t max_n = 160) {
  Geom g;
  g.n = rng.pick(max_n + 1);
  g.p = 1 + rng.pick(8);
  g.uneven = rng.pick(3) == 0;
  if (g.uneven) {
    g.sizes.assign(g.p, 0);
    std::size_t left = g.n;
    for (std::size_t r = 0; r + 1 < g.p; ++r) {
      g.sizes[r] = rng.pick(left + 1);
      left -= g.sizes[r];
    }
    g.sizes[g.p - 1] = left;
  }
  return g;
}

drtpu::distributed_vector<double> make_dv(const Geom& g,
                                          drtpu::halo_bounds hb = {}) {
  if (g.uneven)
    return {g.n, g.p, drtpu::block_distribution(g.sizes), hb};
  return {g.n, g.p, hb};
}

std::vector<double> read_all(drtpu::distributed_vector<double>& dv) {
  std::vector<double> out(dv.size());
  for (std::size_t i = 0; i < dv.size(); ++i) out[i] = dv[i];
  return out;
}

void seed_random(Rng& rng, drtpu::distributed_vector<double>& dv,
                 std::vector<double>& oracle) {
  oracle.resize(dv.size());
  for (std::size_t i = 0; i < dv.size(); ++i) {
    oracle[i] = rng.val();
    dv[i] = oracle[i];
  }
}

// ---------------------------------------------------------------- arms

void arm_segments_invariant(Rng& rng, std::uint64_t seed, int iter) {
  // check_segments oracle: segments tile the range in order, no gaps
  Geom g = draw_geom(rng);
  auto dv = make_dv(g);
  std::vector<double> oracle;
  seed_random(rng, dv, oracle);
  std::size_t at = 0;
  for (auto&& s : drtpu::segments(dv)) {
    for (auto& x : drtpu::local(s)) {
      if (at >= g.n || !close(x, oracle[at])) {
        fail_at("segments", seed, iter, "tiling mismatch");
        return;
      }
      ++at;
    }
  }
  if (at != g.n) fail_at("segments", seed, iter, "coverage != n");
  // rank_of/operator[] agreement on random probes
  for (int k = 0; k < 8 && g.n; ++k) {
    std::size_t i = rng.pick(g.n);
    std::size_t r = dv.rank_of(i);
    if (r >= g.p || dv.valid_of(r) == 0) {
      fail_at("segments", seed, iter, "rank_of out of range/empty");
      return;
    }
    double v = rng.val();
    dv[i] = v;
    if (!close(dv[i], v)) {
      fail_at("segments", seed, iter, "element write/read");
      return;
    }
  }
}

void arm_fill_iota_reduce(Rng& rng, std::uint64_t seed, int iter) {
  Geom g = draw_geom(rng);
  auto dv = make_dv(g);
  if (rng.pick(2)) {
    double v = rng.val();
    drtpu::fill(dv, v);
    double got = drtpu::reduce(dv, 0.0);
    if (!close(got, v * (double)g.n))
      fail_at("fill+reduce", seed, iter, "sum mismatch");
  } else {
    double s0 = rng.val();
    drtpu::iota(dv, s0);
    double want = 0.0;
    for (std::size_t i = 0; i < g.n; ++i) want += s0 + (double)i;
    if (!close(drtpu::reduce(dv, 0.0), want))
      fail_at("iota+reduce", seed, iter, "sum mismatch");
  }
}

void arm_transform_dot(Rng& rng, std::uint64_t seed, int iter) {
  Geom g = draw_geom(rng);
  auto a = make_dv(g);
  std::vector<double> oa;
  seed_random(rng, a, oa);
  // aligned same-geometry output vs misaligned (independent geometry)
  bool aligned = rng.pick(2);
  Geom g2 = aligned ? g : draw_geom(rng);
  auto b = make_dv(g2);
  std::vector<double> ob;
  seed_random(rng, b, ob);
  drtpu::transform(a, b, [](double x) { return 2.0 * x - 1.0; });
  std::size_t m = std::min(g.n, g2.n);
  auto got = read_all(b);
  for (std::size_t i = 0; i < m; ++i)
    if (!close(got[i], 2.0 * oa[i] - 1.0)) {
      fail_at("transform", seed, iter, "value mismatch");
      return;
    }
  for (std::size_t i = m; i < g2.n; ++i)
    if (!close(got[i], ob[i])) {
      fail_at("transform", seed, iter, "tail clobbered");
      return;
    }
  // dot over the same pair
  double want = 0.0;
  auto ga = read_all(a);
  for (std::size_t i = 0; i < m; ++i) want += ga[i] * got[i];
  if (!close(drtpu::dot(a, b, 0.0), want))
    fail_at("dot", seed, iter, "dot mismatch");
}

void arm_scans(Rng& rng, std::uint64_t seed, int iter) {
  Geom g = draw_geom(rng);
  auto a = make_dv(g);
  std::vector<double> oa;
  seed_random(rng, a, oa);
  bool aligned = rng.pick(2);
  Geom g2 = aligned ? g : draw_geom(rng);
  auto out = make_dv(g2);
  std::vector<double> oo;
  seed_random(rng, out, oo);
  std::size_t m = std::min(g.n, g2.n);
  if (rng.pick(2)) {
    drtpu::inclusive_scan(a, out);
    double carry = 0.0;
    auto got = read_all(out);
    for (std::size_t i = 0; i < m; ++i) {
      carry += oa[i];
      if (!close(got[i], carry)) {
        fail_at("inclusive_scan", seed, iter, "prefix mismatch");
        return;
      }
    }
  } else {
    double init = rng.val();
    drtpu::exclusive_scan(a, out, init);
    double carry = init;
    auto got = read_all(out);
    for (std::size_t i = 0; i < m; ++i) {
      if (!close(got[i], carry)) {
        fail_at("exclusive_scan", seed, iter, "prefix mismatch");
        return;
      }
      carry += oa[i];
    }
  }
}

void arm_views(Rng& rng, std::uint64_t seed, int iter) {
  Geom g = draw_geom(rng);
  auto dv = make_dv(g);
  std::vector<double> oracle;
  seed_random(rng, dv, oracle);
  std::size_t d = rng.pick(g.n + 1);
  std::size_t t = rng.pick(g.n - d + 1);
  // drop(d) | take(t) | transform: segment walk equals the oracle slice
  auto v = drtpu::views::transform(
      drtpu::views::take(drtpu::views::drop(dv, d), t),
      [](double x) { return x * x + 0.5; });
  std::size_t at = 0;
  for (auto&& s : drtpu::segments(v)) {
    auto loc = drtpu::local(s);
    for (auto it = loc.begin(); it != loc.end(); ++it, ++at) {
      double want = oracle[d + at] * oracle[d + at] + 0.5;
      if (at >= t || !close(*it, want)) {
        fail_at("views", seed, iter, "drop|take|transform mismatch");
        return;
      }
    }
  }
  if (at != t) fail_at("views", seed, iter, "view length");
  // zip of two same-geometry vectors reduces like the elementwise sum
  auto b = make_dv(g);
  std::vector<double> ob;
  seed_random(rng, b, ob);
  double want = 0.0;
  for (std::size_t i = 0; i < g.n; ++i) want += oracle[i] * ob[i];
  if (!close(drtpu::dot(dv, b, 0.0), want))
    fail_at("views", seed, iter, "zip-dot mismatch");
}

void arm_span_halo(Rng& rng, std::uint64_t seed, int iter) {
  // random halo bounds; constructor may legitimately reject (tail
  // rules) — rejection is a PASS, construction must then be correct
  Geom g = draw_geom(rng, 96);
  g.uneven = false;  // halo requires the uniform layout
  drtpu::halo_bounds hb;
  hb.prev = rng.pick(4);
  hb.next = rng.pick(4);
  hb.periodic = rng.pick(2) == 1;
  drtpu::distributed_vector<double>* dvp = nullptr;
  try {
    dvp = new drtpu::distributed_vector<double>(g.n, g.p, hb);
  } catch (const std::invalid_argument&) {
    return;  // documented rejection surface
  }
  auto& dv = *dvp;
  std::vector<double> oracle;
  seed_random(rng, dv, oracle);
  dv.halo().exchange();
  // oracle: each rank's ghost_prev holds the prev elements before its
  // window; verify through shard_row
  std::size_t seg = dv.segment_size();
  for (std::size_t r = 0; r < g.p; ++r) {
    std::size_t valid = dv.valid_of(r);
    if (!valid) continue;
    auto row = dv.shard_row(r);
    std::size_t start = r * seg;
    if (hb.prev && (r > 0 || hb.periodic)) {
      for (std::size_t k = 0; k < hb.prev; ++k) {
        std::size_t src = (start + g.n - hb.prev + k) % g.n;
        if (r > 0) src = start - hb.prev + k;
        if (!close(row[k], oracle[src])) {
          fail_at("span_halo", seed, iter, "ghost_prev mismatch");
          delete dvp;
          return;
        }
      }
    }
    if (hb.next && (r + 1 < g.p || hb.periodic)) {
      // only LIVE ghost cells are specified: when the neighbor is the
      // short last shard (tail < next), the trailing ghost cells
      // mirror logically nonexistent elements — don't-care (a correct
      // stencil never reads them; the boundary has no neighbors)
      std::size_t live = std::min(hb.next,
                                  dv.valid_of((r + 1) % g.p));
      for (std::size_t k = 0; k < live; ++k) {
        std::size_t src = (start + valid + k) % g.n;
        if (!close(row[hb.prev + valid + k], oracle[src])) {
          fail_at("span_halo", seed, iter, "ghost_next mismatch");
          delete dvp;
          return;
        }
      }
    }
  }
  // reduce(plus): ghosts fold back into owners
  for (std::size_t r = 0; r < g.p; ++r) {
    auto row = dv.shard_row(r);
    for (std::size_t k = 0; k < row.size(); ++k) row[k] = 1.0;
  }
  dv.halo().reduce(drtpu::halo_op::plus);
  double total = drtpu::reduce(dv, 0.0);
  // every live ghost cell added 1.0 somewhere into owned data
  std::size_t ghosts = 0;
  for (std::size_t r = 0; r < g.p; ++r) {
    if (!dv.valid_of(r)) continue;
    // prev-ghosts always fold into live owner cells (every owner of a
    // prev fold has valid >= prev by the ctor rules); next-ghosts
    // folding into the short last shard land in its pads beyond
    // valid, which reduce() never reads — count only the live part
    if (hb.prev && (r > 0 || hb.periodic)) ghosts += hb.prev;
    if (hb.next && (r + 1 < g.p || hb.periodic))
      ghosts += std::min(hb.next, dv.valid_of((r + 1) % g.p));
  }
  if (!close(total, (double)(g.n + ghosts)))
    fail_at("span_halo", seed, iter, "reduce(plus) total");
  delete dvp;
}

void arm_unstructured_halo(Rng& rng, std::uint64_t seed, int iter) {
  Geom g = draw_geom(rng, 96);
  if (g.n == 0) return;
  auto dv = make_dv(g);
  std::vector<double> oracle;
  seed_random(rng, dv, oracle);
  // random ghost map: a few (rank, owned-global-index) edges
  std::map<std::size_t, std::vector<std::size_t>> ghosts;
  std::size_t edges = rng.pick(12);
  for (std::size_t e = 0; e < edges; ++e) {
    std::size_t r = rng.pick(g.p);
    std::size_t i = rng.pick(g.n);
    if (dv.rank_of(i) == r) continue;  // own cell: not a ghost
    ghosts[r].push_back(i);
  }
  try {
    drtpu::unstructured_halo<double> uh(dv, ghosts);
    uh.exchange();
    // exchange: ghost copies equal owners — checked via reduce(plus):
    // bump every ghost by 1 locally is not exposed; instead verify a
    // second exchange after owner writes propagates the new values
    for (std::size_t i = 0; i < g.n; ++i) {
      oracle[i] = rng.val();
      dv[i] = oracle[i];
    }
    uh.exchange();
    uh.reduce(drtpu::halo_op::second);  // second = ghost overwrites
    // owners keep their (latest ghost) value — ghost equals owner, so
    // data must be unchanged
    auto got = read_all(dv);
    for (std::size_t i = 0; i < g.n; ++i)
      if (!close(got[i], oracle[i])) {
        fail_at("unstructured", seed, iter, "exchange/reduce(second)");
        return;
      }
  } catch (const std::invalid_argument&) {
    return;  // documented rejection (e.g. duplicate/out-of-range index)
  }
}

void arm_matrix(Rng& rng, std::uint64_t seed, int iter) {
  // dense tiled matrices with INDEPENDENT random tilings: element
  // round-trip, gemv, and gemm (the SUMMA traversal explicitly
  // supports mismatched tilings of A, B, C) vs triple-loop oracles
  std::size_t m = 1 + rng.pick(24);
  std::size_t k = 1 + rng.pick(24);
  std::size_t n = 1 + rng.pick(24);
  auto tile = [&](std::size_t d) {
    return drtpu::index2d{1 + rng.pick(d), 1 + rng.pick(d)};
  };
  std::size_t p = 1 + rng.pick(8);
  drtpu::dense_matrix<double> A({m, k}, tile(m), drtpu::block_cyclic(p));
  drtpu::dense_matrix<double> B({k, n}, tile(k), drtpu::block_cyclic(p));
  drtpu::dense_matrix<double> C({m, n}, tile(m), drtpu::block_cyclic(p));
  std::vector<double> oa(m * k), ob(k * n);
  for (std::size_t i = 0; i < m; ++i)
    for (std::size_t j = 0; j < k; ++j) {
      oa[i * k + j] = rng.val();
      A(i, j) = oa[i * k + j];
    }
  for (std::size_t i = 0; i < k; ++i)
    for (std::size_t j = 0; j < n; ++j) {
      ob[i * n + j] = rng.val();
      B(i, j) = ob[i * n + j];
    }
  // element round-trip through the tile indexing
  for (int t = 0; t < 6; ++t) {
    std::size_t i = rng.pick(m), j = rng.pick(k);
    if (!close(A(i, j), oa[i * k + j])) {
      fail_at("matrix", seed, iter, "element round-trip");
      return;
    }
  }
  // gemv with accumulate semantics (c starts nonzero)
  std::vector<double> c0(m), bvec(k), want(m);
  for (auto& x : bvec) x = rng.val();
  for (auto& x : c0) x = rng.val();
  std::vector<double> cv = c0;
  drtpu::gemv(cv, A, bvec);
  for (std::size_t i = 0; i < m; ++i) {
    want[i] = c0[i];
    for (std::size_t j = 0; j < k; ++j)
      want[i] += oa[i * k + j] * bvec[j];
    if (!close(cv[i], want[i])) {
      fail_at("matrix", seed, iter, "dense gemv");
      return;
    }
  }
  // gemm across the three independent tilings
  drtpu::gemm(C, A, B);
  for (std::size_t i = 0; i < m; ++i)
    for (std::size_t j = 0; j < n; ++j) {
      double acc = 0.0;
      for (std::size_t kk = 0; kk < k; ++kk)
        acc += oa[i * k + kk] * ob[kk * n + j];
      if (!close(C(i, j), acc)) {
        fail_at("matrix", seed, iter, "gemm mismatched tilings");
        return;
      }
    }
}

void arm_host_sort(Rng& rng, std::uint64_t seed, int iter) {
  // host-executor sort family vs std oracles (mirrors the TPU-side
  // beyond-parity surface on the same vocabulary)
  Geom g = draw_geom(rng);
  auto dv = make_dv(g);
  std::vector<double> oracle;
  seed_random(rng, dv, oracle);
  // sprinkle NaNs sometimes: the numpy contract (NaNs last) must hold
  // and the comparator must stay a strict weak order (review finding)
  if (g.n && rng.pick(3) == 0)
    for (std::size_t k = 0; k < 1 + rng.pick(3); ++k) {
      std::size_t i = rng.pick(g.n);
      oracle[i] = std::nan("");
      dv[i] = oracle[i];
    }
  bool desc = rng.pick(2) == 1;
  drtpu::sort(dv, desc);
  std::vector<double> want = oracle;
  std::stable_sort(want.begin(), want.end(), drtpu::nan_less<double>);
  if (desc) std::reverse(want.begin(), want.end());
  auto got = read_all(dv);
  for (std::size_t i = 0; i < g.n; ++i) {
    bool both_nan = std::isnan(got[i]) && std::isnan(want[i]);
    if (!both_nan && !close(got[i], want[i])) {
      fail_at("host_sort", seed, iter, "sort mismatch");
      return;
    }
  }
  if (drtpu::is_sorted(dv) != !desc && g.n > 1) {
    // descending data of >1 distinct values must read unsorted
    bool distinct = false;
    for (std::size_t i = 1; i < g.n; ++i)
      // NaN-aware inequality: NaN != NaN is true but all-NaN data is
      // NOT distinct under the sort order (review finding)
      if (drtpu::nan_less(got[i], got[0]) ||
          drtpu::nan_less(got[0], got[i]))
        distinct = true;
    if (distinct) {
      fail_at("host_sort", seed, iter, "is_sorted disagrees");
      return;
    }
  }
  // key-value: payload follows the stable key order
  Geom g2 = draw_geom(rng);
  auto k = make_dv(g2);
  auto v = make_dv(g2);
  std::vector<double> ok2, ov2;
  seed_random(rng, k, ok2);
  seed_random(rng, v, ov2);
  drtpu::sort_by_key(k, v, desc);
  std::vector<std::size_t> order(g2.n);
  for (std::size_t i = 0; i < g2.n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [&](std::size_t a, std::size_t b) {
                     return drtpu::nan_less(ok2[a], ok2[b]);
                   });
  if (desc) std::reverse(order.begin(), order.end());
  auto gk = read_all(k);
  auto gv = read_all(v);
  for (std::size_t i = 0; i < g2.n; ++i)
    if (!close(gk[i], ok2[order[i]]) || !close(gv[i], ov2[order[i]])) {
      fail_at("host_sort", seed, iter, "sort_by_key mismatch");
      return;
    }
}

void arm_expr_dsl(Rng& rng, std::uint64_t seed, int iter) {
  // random expression trees: serializer output must stay inside the
  // validated grammar's alphabet and be deterministic (cache-key
  // contract — dr_tpu/utils/expr.py validates exactly this surface)
  std::vector<thp::expr> pool;
  pool.push_back(thp::x0);
  pool.push_back(thp::x1);
  pool.push_back(thp::x2);
  pool.push_back(thp::expr::lit(rng.val()));
  std::size_t steps = 1 + rng.pick(12);
  for (std::size_t k = 0; k < steps; ++k) {
    const thp::expr& a = pool[rng.pick(pool.size())];
    const thp::expr& b = pool[rng.pick(pool.size())];
    switch (rng.pick(9)) {
      case 0: pool.push_back(a + b); break;
      case 1: pool.push_back(a - b); break;
      case 2: pool.push_back(a * b); break;
      case 3: pool.push_back(a / b); break;
      case 4: pool.push_back(thp::min(a, b)); break;
      case 5: pool.push_back(thp::max(a, b)); break;
      case 6: pool.push_back(thp::abs(a)); break;
      case 7: pool.push_back(thp::sqrt(a)); break;
      case 8: pool.push_back(a + thp::expr::lit(rng.val())); break;
    }
  }
  const std::string s = pool.back().str();
  const std::string again = pool.back().str();
  if (s != again) {
    fail_at("expr", seed, iter, "non-deterministic serialization");
    return;
  }
  // alphabet check: identifiers, digits, and DSL punctuation only
  // (the same character set dr_tpu/utils/expr.py's _PUNCT accepts)
  int depth = 0;
  for (char ch : s) {
    bool ok = (ch >= 'a' && ch <= 'z') || (ch >= '0' && ch <= '9') ||
              std::strchr(" ()+-*/.,", ch) != nullptr;
    if (ch == '(') ++depth;
    if (ch == ')') --depth;
    if (!ok || depth < 0) {
      fail_at("expr", seed, iter, "serialized outside DSL alphabet");
      return;
    }
  }
  if (depth != 0) fail_at("expr", seed, iter, "unbalanced parens");
  if (s.find("__") != std::string::npos)
    fail_at("expr", seed, iter, "double underscore leaked");
}

}  // namespace

int main(int argc, char** argv) {
  long iters = argc > 1 ? std::atol(argv[1]) : 1000;
  std::uint64_t seed = argc > 2
      ? (std::uint64_t)std::strtoull(argv[2], nullptr, 10)
      : (std::uint64_t)time(nullptr) * 2654435761u;
  std::printf("fuzz_native: %ld iterations, seed=%llu (replay: "
              "fuzz_native %ld %llu)\n",
              iters, (unsigned long long)seed, iters,
              (unsigned long long)seed);
  Rng rng(seed);
  for (int i = 0; i < iters; ++i) {
    switch (rng.pick(10)) {
      case 0: arm_segments_invariant(rng, seed, i); break;
      case 1: arm_fill_iota_reduce(rng, seed, i); break;
      case 2: arm_transform_dot(rng, seed, i); break;
      case 3: arm_scans(rng, seed, i); break;
      case 4: arm_views(rng, seed, i); break;
      case 5: arm_span_halo(rng, seed, i); break;
      case 6: arm_unstructured_halo(rng, seed, i); break;
      case 7: arm_expr_dsl(rng, seed, i); break;
      case 8: arm_matrix(rng, seed, i); break;
      case 9: arm_host_sort(rng, seed, i); break;
    }
    if (failures > 10) break;  // enough signal; keep the log readable
  }
  if (failures) {
    std::printf("fuzz_native: %d FAILURES (seed=%llu)\n", failures,
                (unsigned long long)seed);
    return 1;
  }
  std::printf("fuzz_native: all %ld iterations PASSED\n", iters);
  return 0;
}
