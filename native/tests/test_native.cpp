// Native layer tests: vocabulary CPOs, concepts, segment tools, the host
// distributed_vector with halo, and the algorithm set — assert-based, run
// at several logical mesh sizes (the native analog of the reference's
// mpiexec -n {1,2,3,4} sweep, test/gtest/mhp/CMakeLists.txt:27-33).
#include <cassert>
#include <cmath>
#include <cstdio>
#include <numeric>
#include <vector>

#include <drtpu/algorithms.hpp>
#include <drtpu/communicator.hpp>
#include <drtpu/distributed_vector.hpp>
#include <drtpu/iterator_adaptor.hpp>
#include <drtpu/matrix.hpp>
#include <drtpu/remote_span.hpp>
#include <drtpu/segment_tools.hpp>
#include <drtpu/unstructured_halo.hpp>
#include <drtpu/views.hpp>
#include <drtpu/vocabulary.hpp>

using drtpu::distributed_vector;
using drtpu::halo_bounds;
using drtpu::remote_span;

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                                \
      return 1;                                                           \
    }                                                                     \
  } while (0)

static int test_concepts() {
  static_assert(drtpu::remote_range<remote_span<int>>);
  static_assert(drtpu::remote_contiguous_range<remote_span<int>>);
  static_assert(drtpu::distributed_range<distributed_vector<double>&>);
  static_assert(!drtpu::remote_range<std::vector<int>>);
  return 0;
}

static int test_vocabulary(std::size_t P) {
  distributed_vector<double> dv(23, P);
  auto segs = drtpu::segments(dv);
  std::size_t total = 0, prev_rank = 0;
  bool first = true;
  for (auto& s : segs) {
    total += s.size();
    CHECK(drtpu::rank(s) < P);
    if (!first) CHECK(drtpu::rank(s) > prev_rank);
    prev_rank = drtpu::rank(s);
    first = false;
  }
  CHECK(total == 23);
  // local() yields writable host spans
  drtpu::iota(dv, 0.0);
  for (auto& s : segs) {
    auto loc = drtpu::local(s);
    CHECK(loc.size() == s.size());
    CHECK(loc[0] == static_cast<double>(s.origin()));
  }
  return 0;
}

static int test_segment_tools(std::size_t P) {
  distributed_vector<int> dv(24, P);
  drtpu::iota(dv, 0);
  auto segs = dv.dr_segments();
  auto taken = drtpu::take_segments(segs, 7);
  std::size_t tn = 0;
  for (auto& s : taken) tn += s.size();
  CHECK(tn == 7);
  auto dropped = drtpu::drop_segments(segs, 5);
  std::size_t dn = 0;
  for (auto& s : dropped) dn += s.size();
  CHECK(dn == 19);
  CHECK(dropped[0][0] == 5);
  auto sub = drtpu::subrange_segments(segs, 3, 11);
  int expect = 3;
  for (auto& s : sub)
    for (int v : s) CHECK(v == expect++);
  CHECK(expect == 11);

  distributed_vector<int> other(24, P);
  CHECK(drtpu::aligned(dv, other));
  distributed_vector<int> longer(100, P);
  if (P > 1) CHECK(!drtpu::aligned(dv, longer));
  return 0;
}

static int test_algorithms(std::size_t P) {
  distributed_vector<double> a(50, P), b(50, P);
  drtpu::iota(a, 1.0);
  drtpu::transform(a, b, [](double x) { return 2 * x; });
  CHECK(b[49] == 100.0);
  double sum = drtpu::reduce(a, 0.0);
  CHECK(sum == 50.0 * 51.0 / 2.0);
  double sq = drtpu::transform_reduce(a, 0.0, std::plus<>{},
                                      [](double x) { return x * x; });
  CHECK(sq == 42925.0);
  double d = drtpu::dot(a, a, 0.0);
  CHECK(d == sq);
  distributed_vector<double> s(50, P);
  drtpu::inclusive_scan(a, s);
  CHECK(s[49] == sum);
  drtpu::fill(b, 7.0);
  CHECK(drtpu::reduce(b, 0.0) == 350.0);
  // iterator + misaligned fallback path
  drtpu::for_each(a, [](double& x) { x += 1.0; });
  CHECK(a[0] == 2.0);
  CHECK(*a.begin() == 2.0);
  CHECK(*(a.begin() + 49) == 51.0);
  CHECK(a.end() - a.begin() == 50);
  return 0;
}

static int test_halo(std::size_t P) {
  std::size_t n = 8 * P;
  distributed_vector<double> dv(n, P, halo_bounds{1, 1, false});
  drtpu::iota(dv, 0.0);
  dv.halo().exchange();
  if (P > 1) {
    // ghost_prev of rank 1 holds rank 0's last owned value
    auto row1 = dv.shard_row(1);
    CHECK(row1[0] == static_cast<double>(dv.segment_size() - 1));
  }
  // periodic ring with a short last shard ships the logical tail
  std::size_t n2 = 8 * P - (P > 1 ? 3 : 0);
  distributed_vector<double> ring(n2, P, halo_bounds{1, 1, true});
  drtpu::iota(ring, 0.0);
  ring.halo().exchange();
  auto row0 = ring.shard_row(0);
  CHECK(row0[0] == static_cast<double>(n2 - 1));
  // ghost->owner reduction
  distributed_vector<double> r2(8 * P, P, halo_bounds{1, 1, false});
  drtpu::fill(r2, 1.0);
  r2.halo().exchange();
  r2.halo().reduce_plus();
  if (P > 1) {
    CHECK(r2[dv.segment_size() - 1] == 2.0);
    CHECK(r2[0] == 1.0);
  }
  // stencil through the padded rows (the hot-loop shape)
  distributed_vector<double> in(8 * P, P, halo_bounds{1, 1, false});
  distributed_vector<double> out(8 * P, P, halo_bounds{1, 1, false});
  drtpu::iota(in, 0.0);
  in.halo().exchange();
  for (std::size_t r = 0; r < P; ++r) {
    auto row = in.shard_row(r);
    auto orow = out.shard_row(r);
    for (std::size_t j = 0; j < in.valid_of(r); ++j)
      orow[1 + j] = (row[j] + row[j + 1] + row[j + 2]) / 3.0;
  }
  for (std::size_t i = 1; i + 1 < in.size(); ++i)
    CHECK(std::abs(out[i] - static_cast<double>(i)) < 1e-9);
  return 0;
}

static int test_regressions(std::size_t P) {
  // moved/copied vector's halo controller must act on the new object
  distributed_vector<double> a(16 * P, P, halo_bounds{1, 1, false});
  drtpu::iota(a, 0.0);
  auto b = std::move(a);
  b.halo().exchange();
  if (P > 1) CHECK(b.shard_row(1)[0] == double(b.segment_size() - 1));
  distributed_vector<double> c = b;
  drtpu::fill(c, 5.0);
  c.halo().exchange();
  CHECK(c[0] == 5.0);
  CHECK(b[0] == 0.0);  // source untouched by the copy's halo

  // misaligned dot/scan/transform fall back over the common prefix
  distributed_vector<double> x(100, P), y(3, P);
  drtpu::fill(x, 2.0);
  drtpu::fill(y, 3.0);
  CHECK(drtpu::dot(x, y, 0.0) == 18.0);
  distributed_vector<double> in(60, P), out(50, P);
  drtpu::fill(in, 1.0);
  drtpu::inclusive_scan(in, out);
  CHECK(out[49] == 50.0);
  drtpu::transform(in, out, [](double v) { return v * 4; });
  CHECK(out[49] == 4.0);
  return 0;
}

static int test_views(std::size_t P) {
  namespace vw = drtpu::views;
  distributed_vector<double> dv(40, P);
  drtpu::iota(dv, 0.0);

  // take/drop/subrange pipelines recompute segments
  auto t = dv | vw::take(13);
  static_assert(drtpu::distributed_range<decltype(t)>);
  CHECK(t.size() == 13);
  CHECK(drtpu::reduce(t, 0.0) == 12.0 * 13.0 / 2.0);
  auto d = dv | vw::drop(35);
  CHECK(d.size() == 5);
  CHECK(*d.begin() == 35.0);
  auto sub = dv | vw::subrange(10, 20);
  CHECK(drtpu::reduce(sub, 0.0) == (10.0 + 19.0) * 10.0 / 2.0);
  // segments join back to the view (check_segments invariant)
  double joined = 0;
  std::size_t count = 0;
  for (auto& s : drtpu::segments(sub))
    for (auto&& v : drtpu::local(s)) { joined += v; ++count; }
  CHECK(count == 10 && joined == drtpu::reduce(sub, 0.0));

  // transform stays distributed; transform | reduce == transform_reduce
  auto sq = dv | vw::transform([](double x) { return x * x; });
  CHECK(drtpu::segments(sq).size() == drtpu::segments(dv).size());
  double ssq = drtpu::reduce(sq, 0.0);
  CHECK(ssq == drtpu::transform_reduce(dv, 0.0, std::plus<>{},
                                       [](double x) { return x * x; }));

  // zip: aligned views zip segment-wise; elementwise iteration works
  distributed_vector<double> other(40, P);
  drtpu::fill(other, 2.0);
  auto z = vw::zip(dv, other);
  CHECK(z.size() == 40);
  CHECK(!drtpu::segments(z).empty());
  double dotv = 0;
  for (auto& s : drtpu::segments(z))
    for (auto&& [a, b] : drtpu::local(s)) dotv += a * b;
  CHECK(dotv == drtpu::dot(dv, other, 0.0));
  {
    auto [a0, b0] = *z.begin();
    CHECK(a0 == 0.0 && b0 == 2.0);
  }
  // zip of dv with a shifted self: misaligned => empty segments signal
  if (P > 1) {
    auto zm = vw::zip(dv, dv | vw::drop(1));
    CHECK(drtpu::segments(zm).empty());
    // nested zip over a misaligned zip propagates the empty signal
    // instead of indexing the inner empty segment list
    auto zz = vw::zip(zm, dv);
    CHECK(drtpu::segments(zz).empty());
  }
  // zip | transform | reduce — the dot-product pipeline
  // (examples/shp/dot_product.cpp:11-18 shape)
  auto prod = vw::zip(dv, other) |
              vw::transform([](auto t) {
                auto [a, b] = t;
                return a * b;
              });
  CHECK(drtpu::reduce(prod, 0.0) == dotv);

  // enumerate carries global indices through segments
  auto en = vw::enumerate(dv);
  for (auto& s : drtpu::segments(en))
    for (auto&& [i, v] : drtpu::local(s))
      CHECK(static_cast<double>(i) == v);

  // ranked view reports owning ranks
  auto pairs = vw::ranked(dv).pairs();
  CHECK(pairs.size() == 40);
  CHECK(pairs.front().first == 0);
  CHECK(pairs.back().first == drtpu::rank(
      drtpu::segments(dv).back()));
  return 0;
}

static int test_matrix(std::size_t P) {
  using drtpu::index2d;
  // block-cyclic placement covers all ranks; grid is near-square
  auto grid = drtpu::factor_grid(P);
  CHECK(grid.i * grid.j == P);
  CHECK(grid.i >= grid.j);

  // dense matrix: tiles join back to the logical matrix
  drtpu::dense_matrix<double> A(index2d{10, 7}, P);
  for (std::size_t i = 0; i < 10; ++i)
    for (std::size_t j = 0; j < 7; ++j) A(i, j) = 10.0 * i + j;
  std::size_t covered = 0;
  for (auto& t : A.dr_segments()) {
    CHECK(t.dr_rank() < P);
    covered += t.size();
    for (std::size_t i = 0; i < t.shape().i; ++i)
      for (std::size_t j = 0; j < t.shape().j; ++j)
        CHECK(t(i, j) == 10.0 * (t.origin().i + i) + (t.origin().j + j));
  }
  CHECK(covered == 70);

  // dense gemv vs serial oracle
  std::vector<double> b(7), c(10, 0.0), ref(10, 0.0);
  for (std::size_t j = 0; j < 7; ++j) b[j] = 1.0 + j;
  drtpu::gemv(c, A, b);
  for (std::size_t i = 0; i < 10; ++i)
    for (std::size_t j = 0; j < 7; ++j) ref[i] += A(i, j) * b[j];
  for (std::size_t i = 0; i < 10; ++i)
    CHECK(std::abs(c[i] - ref[i]) < 1e-9);

  // gemm vs serial oracle
  drtpu::dense_matrix<double> B(index2d{7, 6}, P), C(index2d{10, 6}, P);
  for (std::size_t i = 0; i < 7; ++i)
    for (std::size_t j = 0; j < 6; ++j) B(i, j) = (i == j) ? 2.0 : 0.0;
  drtpu::gemm(C, A, B);
  for (std::size_t i = 0; i < 10; ++i)
    for (std::size_t j = 0; j < 6; ++j) CHECK(C(i, j) == 2.0 * A(i, j));

  // sparse CSR from COO + SpMV vs dense oracle
  std::vector<std::tuple<std::size_t, std::size_t, double>> coo;
  for (std::size_t i = 0; i < 10; ++i)
    for (std::size_t j = 0; j < 7; ++j)
      if ((i + j) % 3 == 0) coo.emplace_back(i, j, 1.0 + double(i * 7 + j));
  drtpu::sparse_matrix<double> S(index2d{10, 7}, P, coo);
  CHECK(S.nnz() == coo.size());
  std::vector<double> sc(10, 0.0), sref(10, 0.0);
  drtpu::gemv(sc, S, b);
  for (auto& [i, j, v] : coo) sref[i] += v * b[j];
  for (std::size_t i = 0; i < 10; ++i)
    CHECK(std::abs(sc[i] - sref[i]) < 1e-9);

  // 2-D sparse tile grid: tiles window both axes, SpMV accumulates
  // per-tile partials (the reference asserts grid cols == 1 away;
  // gemv.hpp:21)
  {
    index2d grid{P >= 2 ? P / 2 : std::size_t{1},
                 P >= 2 ? std::size_t{2} : std::size_t{1}};
    drtpu::sparse_matrix<double> S2(index2d{10, 7}, grid, coo);
    CHECK(S2.nnz() == coo.size());
    CHECK(S2.grid_shape().i == grid.i && S2.grid_shape().j == grid.j);
    std::size_t nnz2 = 0;
    for (auto& t : S2.tiles()) {
      nnz2 += t.nnz();
      for (std::size_t li = 0; li < t.shape.i; ++li)
        for (auto k = t.rowptr[li]; k < t.rowptr[li + 1]; ++k)
          CHECK(t.colind[k] < t.shape.j);  // tile-local columns
    }
    CHECK(nnz2 == coo.size());
    std::vector<double> sc2(10, 0.0);
    drtpu::gemv(sc2, S2, b);
    for (std::size_t i = 0; i < 10; ++i)
      CHECK(std::abs(sc2[i] - sref[i]) < 1e-9);
  }
  return 0;
}

static int test_distribution(std::size_t P) {
  using drtpu::block_distribution;
  using drtpu::distributed_vector;

  // uneven blocks: rank r owns sizes[r] contiguous elements
  std::size_t n = 4 * P + 3;
  std::vector<std::size_t> sizes(P, 4);
  sizes[0] += 3;  // lopsided first block
  distributed_vector<double> dv(n, P, block_distribution(sizes));
  CHECK(!dv.uniform() || P == 1);
  drtpu::iota(dv, 0.0);
  for (std::size_t i = 0; i < n; ++i) CHECK(dv[i] == double(i));

  // segments carry the declared sizes, in order, ranks increasing
  auto segs = dv.dr_segments();
  CHECK(segs.size() == P);
  std::size_t at = 0;
  for (std::size_t r = 0; r < P; ++r) {
    CHECK(drtpu::rank(segs[r]) == r);
    CHECK(segs[r].size() == sizes[r]);
    CHECK(segs[r].origin() == at);
    at += sizes[r];
  }

  // algorithms run segment-wise over the uneven layout
  CHECK(drtpu::reduce(dv, 0.0) == double(n) * double(n - 1) / 2.0);
  distributed_vector<double> out(n, P, block_distribution(sizes));
  drtpu::transform(dv, out, [](double x) { return 2.0 * x; });
  for (std::size_t i = 0; i < n; ++i) CHECK(out[i] == 2.0 * double(i));
  drtpu::inclusive_scan(dv, out);
  double acc = 0;
  for (std::size_t i = 0; i < n; ++i) {
    acc += double(i);
    CHECK(out[i] == acc);
  }

  // zero-size blocks = teams: everything on the last rank
  std::vector<std::size_t> team(P, 0);
  team[P - 1] = 6;
  distributed_vector<int> tv(6, P, block_distribution(team));
  drtpu::fill(tv, 9);
  auto tsegs = tv.dr_segments();
  CHECK(tsegs.size() == 1 && drtpu::rank(tsegs[0]) == P - 1);
  CHECK(tv[5] == 9);

  // validation: wrong sum / wrong count / halo-with-uneven all throw
  bool threw = false;
  try {
    distributed_vector<double> bad(n + 1, P, block_distribution(sizes));
    (void)bad;
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  threw = false;
  try {
    std::vector<std::size_t> wrong(P + 1, 1);
    distributed_vector<double> bad2(P + 1, P, block_distribution(wrong));
    (void)bad2;
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  if (P > 1) {
    threw = false;
    try {
      distributed_vector<double> bad3(
          n, P, block_distribution(sizes), drtpu::halo_bounds{1, 1});
      (void)bad3;
    } catch (const std::invalid_argument&) {
      threw = true;
    }
    CHECK(threw);
  }

  // P == 1 periodic self-wrap below the radius must be rejected (the
  // exchange would read pad cells — round-5 native-fuzz finding; the
  // Python container rejects the same shape)
  threw = false;
  try {
    distributed_vector<double> bad4(2, 1, drtpu::halo_bounds{3, 0, true});
    (void)bad4;
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  {
    // ...and AT the radius it is legal and wraps correctly
    distributed_vector<double> okp(3, 1, drtpu::halo_bounds{3, 0, true});
    drtpu::iota(okp, 1.0);
    okp.halo().exchange();
    auto row = okp.shard_row(0);
    CHECK(row[0] == 1.0 && row[1] == 2.0 && row[2] == 3.0);
  }

  // explicitly-even sizes behave as the default layout (uniform fast path)
  std::size_t m = 8 * P;
  std::vector<std::size_t> even(P, 8);
  distributed_vector<double> ev(m, P, block_distribution(even));
  CHECK(ev.uniform());
  drtpu::iota(ev, 1.0);
  CHECK(drtpu::reduce(ev, 0.0) == double(m) * double(m + 1) / 2.0);

  // halo-bumped segment size: even-under-ceil sizes are NOT the default
  // layout when the halo radius exceeds the block size — must be rejected
  // (the default ctor rejects the same config), never silently misindexed
  if (P == 4) {
    threw = false;
    try {
      distributed_vector<double> hb_bad(
          8, 4, block_distribution({2, 2, 2, 2}), drtpu::halo_bounds{3, 0});
      (void)hb_bad;
    } catch (const std::invalid_argument&) {
      threw = true;
    }
    CHECK(threw);
  }
  // ...while explicit sizes matching the halo-bumped default layout ARE
  // uniform and index identically to a default-constructed peer
  if (P == 2) {
    distributed_vector<double> hv(8, 2, block_distribution({6, 2}),
                                  drtpu::halo_bounds{6, 0});
    CHECK(hv.uniform());
    drtpu::iota(hv, 0.0);
    distributed_vector<double> hd(8, 2, drtpu::halo_bounds{6, 0});
    drtpu::iota(hd, 0.0);
    for (std::size_t i = 0; i < 8; ++i) CHECK(hv[i] == hd[i]);
    auto hs = hv.dr_segments();
    auto ds = hd.dr_segments();
    CHECK(hs.size() == ds.size());
    for (std::size_t k = 0; k < hs.size(); ++k)
      CHECK(hs[k].size() == ds[k].size() &&
            hs[k].origin() == ds[k].origin());
  }
  return 0;
}

static int test_communicator(std::size_t P) {
  drtpu::communicator comm(P);
  CHECK(comm.size() == P && comm.first() == 0 && comm.last() == P - 1);
  CHECK(comm.next(P - 1) == 0 && comm.prev(0) == P - 1);
  comm.barrier();

  // bcast: root's slot lands everywhere
  std::vector<double> slots(P);
  for (std::size_t r = 0; r < P; ++r) slots[r] = double(r);
  comm.bcast(slots, P - 1);
  for (auto v : slots) CHECK(v == double(P - 1));

  // scatter / gather round-trip in rank order
  std::vector<double> vals(P), got;
  for (std::size_t r = 0; r < P; ++r) vals[r] = 10.0 + double(r);
  comm.scatter(vals, slots);
  comm.gather(slots, got);
  CHECK(got == vals);

  // ring shifts: non-periodic keeps the edge, periodic wraps
  comm.scatter(vals, slots);
  comm.shift_forward(slots, /*periodic=*/false);
  CHECK(slots[0] == vals[0]);  // edge kept
  if (P > 1) CHECK(slots[1] == vals[0] && slots[P - 1] == vals[P - 2]);
  comm.scatter(vals, slots);
  comm.shift_backward(slots, /*periodic=*/true);
  CHECK(slots[P - 1] == vals[0]);
  if (P > 1) CHECK(slots[0] == vals[1]);

  // alltoall transposes the mailbox grid; in-place aliasing is safe
  std::vector<std::vector<double>> grid(P, std::vector<double>(P)), tg;
  for (std::size_t r = 0; r < P; ++r)
    for (std::size_t c = 0; c < P; ++c) grid[r][c] = double(r * P + c);
  comm.alltoall(grid, tg);
  for (std::size_t r = 0; r < P; ++r)
    for (std::size_t c = 0; c < P; ++c) CHECK(tg[c][r] == grid[r][c]);
  comm.alltoall(tg, tg);  // transpose back in place
  CHECK(tg == grid);

  // out-of-range bcast root throws instead of reading past the slots
  bool threw = false;
  try {
    comm.bcast(slots, P);
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  return 0;
}

static int test_unstructured_halo(std::size_t P) {
  using drtpu::unstructured_halo;
  std::size_t n = 6 * P;
  distributed_vector<double> dv(n, P);
  drtpu::iota(dv, 0.0);

  // every rank mirrors the first and last global element plus a middle one
  std::map<std::size_t, std::vector<std::size_t>> ghosts;
  for (std::size_t r = 0; r < P; ++r)
    ghosts[r] = {0, n / 2, n - 1};
  unstructured_halo<double> uh(dv, ghosts);

  uh.exchange();
  for (std::size_t r = 0; r < P; ++r) {
    auto g = uh.ghost_values(r);
    CHECK(g.size() == 3);
    CHECK(g[0] == 0.0 && g[1] == double(n / 2) && g[2] == double(n - 1));
  }

  // contributions fold back into owners (plus), duplicates accumulate:
  // every rank contributes 1.0 to each mirrored element
  for (std::size_t r = 0; r < P; ++r) {
    std::vector<double> ones(3, 1.0);
    uh.set_ghost_values(r, std::span<const double>(ones));
  }
  uh.reduce(drtpu::halo_op::plus);
  CHECK(dv[0] == 0.0 + double(P));
  CHECK(dv[n / 2] == double(n / 2) + double(P));
  CHECK(dv[n - 1] == double(n - 1) + double(P));

  // op=second overwrites (last contribution wins over duplicates)
  for (std::size_t r = 0; r < P; ++r) {
    std::vector<double> v = {5.0, 6.0, 7.0};
    uh.set_ghost_values(r, std::span<const double>(v));
  }
  uh.reduce(drtpu::halo_op::second);
  CHECK(dv[0] == 5.0 && dv[n / 2] == 6.0 && dv[n - 1] == 7.0);

  // validation: out-of-range index / rank throw
  bool threw = false;
  try {
    std::map<std::size_t, std::vector<std::size_t>> bad{{0, {n}}};
    unstructured_halo<double> b(dv, bad);
    (void)b;
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  // out-of-range rank throws even with an empty index list
  threw = false;
  try {
    std::map<std::size_t, std::vector<std::size_t>> bad{{P + 7, {}}};
    unstructured_halo<double> b(dv, bad);
    (void)b;
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  return 0;
}


static int test_rma_window(std::size_t P) {
  // lib::rma_window analog: per-rank blocks, one-sided get/put
  std::vector<std::vector<double>> blocks(P, std::vector<double>(4, 0.0));
  drtpu::rma_window<double> win(P);
  for (std::size_t r = 0; r < P; ++r)
    win.create(r, blocks[r].data(), blocks[r].size());
  for (std::size_t r = 0; r < P; ++r) win.put(r, 1, 10.0 * r);
  win.fence();
  for (std::size_t r = 0; r < P; ++r) {
    CHECK(win.get(r, 1) == 10.0 * r);
    CHECK(blocks[r][1] == 10.0 * r);
    win.flush(r);
    CHECK(win.size(r) == 4);
  }
  bool threw = false;
  try {
    win.get(0, 99);
  } catch (const std::out_of_range&) {
    threw = true;
  }
  CHECK(threw);
  win.free_window();
  threw = false;
  try {
    win.get(0, 0);
  } catch (const std::logic_error&) {
    threw = true;
  }
  CHECK(threw);
  return 0;
}

static int test_exclusive_scan(std::size_t P) {
  std::size_t n = 4 * P + 3;
  distributed_vector<double> in(n, P), out(n, P);
  drtpu::iota(in, 1.0);
  drtpu::exclusive_scan(in, out, 100.0);
  double carry = 100.0;
  for (std::size_t i = 0; i < n; ++i) {
    CHECK(out[i] == carry);
    carry += in[i];
  }
  return 0;
}

int test_segment_range() {
  // shp/range.hpp:97-130: per-segment id range with global offsets
  drtpu::segment_range sr(3, 4, 100);
  CHECK(sr.size() == 4);
  CHECK(sr.dr_rank() == 0);
  std::size_t i = 0;
  for (auto id : sr) {
    CHECK(id.segment() == 3);
    CHECK(id.local_id() == i);
    CHECK(id.global_id() == 100 + i);
    CHECK(std::size_t(id) == 100 + i);  // converts to the global index
    ++i;
  }
  CHECK(i == 4);
  CHECK(sr[2].global_id() == 102);
  CHECK(sr.end() - sr.begin() == 4);
  static_assert(std::random_access_iterator<
                decltype(drtpu::segment_range(0, 0, 0).begin())>);
  return 0;
}

static int test_host_sort(std::size_t P) {
  using drtpu::distributed_vector;
  // NaN contract: NaNs rank LAST ascending (the TPU path's numpy
  // order), sort is stable, and sort_by_key validates lengths
  distributed_vector<double> v(7, P);
  double vals[] = {3.0, std::nan(""), 1.0, 2.0, std::nan(""), 0.5, 4.0};
  for (std::size_t i = 0; i < 7; ++i) v[i] = vals[i];
  CHECK(!drtpu::is_sorted(v));
  drtpu::sort(v);
  CHECK(v[0] == 0.5 && v[1] == 1.0 && v[2] == 2.0 && v[3] == 3.0 &&
        v[4] == 4.0 && std::isnan(v[5]) && std::isnan(v[6]));
  CHECK(drtpu::is_sorted(v));
  // [1.0, nan] is sorted; [nan, 1.0] is not
  distributed_vector<double> w(2, P);
  w[0] = 1.0;
  w[1] = std::nan("");
  CHECK(drtpu::is_sorted(w));
  w[0] = std::nan("");
  w[1] = 1.0;
  CHECK(!drtpu::is_sorted(w));
  // STABILITY: duplicate keys keep their payloads in original order
  distributed_vector<double> dk(6, P), dp(6, P);
  double kv[] = {2.0, 1.0, 2.0, 1.0, 2.0, 1.0};
  for (std::size_t i = 0; i < 6; ++i) {
    dk[i] = kv[i];
    dp[i] = (double)i;
  }
  drtpu::sort_by_key(dk, dp);
  // ascending stable: 1-keys' payloads 1,3,5 then 2-keys' 0,2,4
  CHECK(dp[0] == 1.0 && dp[1] == 3.0 && dp[2] == 5.0 &&
        dp[3] == 0.0 && dp[4] == 2.0 && dp[5] == 4.0);

  // mismatched key/value lengths fail cleanly, never read OOB
  distributed_vector<double> k(4, P), p2(6, P);
  bool threw = false;
  try {
    drtpu::sort_by_key(k, p2);
  } catch (const std::invalid_argument&) {
    threw = true;
  }
  CHECK(threw);
  return 0;
}

int main() {
  if (test_concepts()) return 1;
  if (test_segment_range()) return 1;
  for (std::size_t P : {1, 2, 3, 4, 8}) {
    if (test_vocabulary(P)) return 1;
    if (test_segment_tools(P)) return 1;
    if (test_algorithms(P)) return 1;
    if (test_halo(P)) return 1;
    if (test_regressions(P)) return 1;
    if (test_views(P)) return 1;
    if (test_matrix(P)) return 1;
    if (test_distribution(P)) return 1;
    if (test_communicator(P)) return 1;
    if (test_unstructured_halo(P)) return 1;
    if (test_rma_window(P)) return 1;
    if (test_exclusive_scan(P)) return 1;
    if (test_host_sort(P)) return 1;
  }
  {
    // logger: no-op until a sink is set; writes call-site-prefixed lines
    char path[] = "/tmp/drtpu_log_test.txt";
    DRTPU_LOG("dropped (no sink yet), value=%d", 1);
    drtpu::drlog.set_file(path);
    DRTPU_LOG("halo exchange rank=%d n=%zu", 3, std::size_t{42});
    drtpu::drlog.close();
    std::FILE* f = std::fopen(path, "r");
    CHECK(f != nullptr);
    char buf[256] = {0};
    CHECK(std::fgets(buf, sizeof buf, f) != nullptr);
    std::fclose(f);
    std::remove(path);
    std::string line(buf);
    CHECK(line.find("test_native.cpp") != std::string::npos);
    CHECK(line.find("halo exchange rank=3 n=42") != std::string::npos);
    CHECK(line.find("dropped") == std::string::npos);
  }
  std::printf("native tests PASSED\n");
  return 0;
}
