// Native distributed algorithms over the vocabulary: fill / iota / copy /
// for_each / transform / reduce / transform_reduce / inclusive_scan —
// segment-wise execution with the aligned fast path / element fallback
// split of the reference (mhp/algorithms/cpu_algorithms.hpp:14-167,
// shp/algorithms/*).  On this host executor "element fallback" is plain
// indexing (no RMA needed); on the TPU executor the same surface lowers to
// fused XLA programs (dr_tpu/algorithms/*).
#pragma once

#include <algorithm>
#include <cmath>
#include <functional>
#include <numeric>

#include "distributed_vector.hpp"
#include "segment_tools.hpp"
#include "vocabulary.hpp"

namespace drtpu {

template <distributed_range R, class T>
void fill(R&& r, T value) {
  for (auto&& s : drtpu::segments(r))
    for (auto& x : drtpu::local(s)) x = value;
}

template <class T>
void iota(distributed_vector<T>& dv, T start) {
  for (auto&& s : drtpu::segments(dv)) {
    T v = start + static_cast<T>(s.origin());
    for (auto& x : drtpu::local(s)) x = v++;
  }
}

template <distributed_range In, distributed_range Out, class Op>
void transform(In&& in, Out&& out, Op op) {
  if (drtpu::aligned(in, out)) {
    auto is = drtpu::local_segments(in);
    auto os = drtpu::local_segments(out);
    for (std::size_t k = 0; k < is.size(); ++k)
      for (std::size_t i = 0; i < is[k].size(); ++i)
        os[k][i] = op(is[k][i]);
    return;
  }
  // misaligned fallback: element-wise up to the shorter range
  std::size_t n = std::min<std::size_t>(std::ranges::size(in),
                                        std::ranges::size(out));
  auto ib = std::ranges::begin(in);
  auto ob = std::ranges::begin(out);
  for (std::size_t i = 0; i < n; ++i, ++ib, ++ob) *ob = op(*ib);
}

template <distributed_range In, distributed_range Out>
void copy(In&& in, Out&& out) {
  transform(in, out, [](auto x) { return x; });
}

template <distributed_range R, class Fn>
void for_each(R&& r, Fn fn) {
  for (auto&& s : drtpu::segments(r))
    for (auto& x : drtpu::local(s)) fn(x);
}

template <distributed_range R, class T, class Op = std::plus<>>
T reduce(R&& r, T init = T{}, Op op = {}) {
  T acc = init;
  for (auto&& s : drtpu::segments(r)) {
    auto loc = drtpu::local(s);
    acc = std::reduce(loc.begin(), loc.end(), acc, op);
  }
  return acc;  // valid on every rank (single controller)
}

template <distributed_range R, class T, class ROp = std::plus<>,
          class TOp = std::identity>
T transform_reduce(R&& r, T init = T{}, ROp rop = {}, TOp top = {}) {
  T acc = init;
  for (auto&& s : drtpu::segments(r)) {
    auto loc = drtpu::local(s);
    acc = std::transform_reduce(loc.begin(), loc.end(), acc, rop, top);
  }
  return acc;
}

// dot = zip | transform | reduce (examples/shp/dot_product.cpp:11-18)
template <distributed_range A, distributed_range B, class T>
T dot(A&& a, B&& b, T init = T{}) {
  T acc = init;
  if (drtpu::aligned(a, b)) {
    auto as = drtpu::local_segments(a);
    auto bs = drtpu::local_segments(b);
    for (std::size_t k = 0; k < as.size(); ++k)
      for (std::size_t i = 0; i < as[k].size(); ++i)
        acc += as[k][i] * bs[k][i];
    return acc;
  }
  // misaligned fallback over the common prefix
  std::size_t n = std::min<std::size_t>(std::ranges::size(a),
                                        std::ranges::size(b));
  auto ai = std::ranges::begin(a);
  auto bi = std::ranges::begin(b);
  for (std::size_t i = 0; i < n; ++i, ++ai, ++bi) acc += (*ai) * (*bi);
  return acc;
}

// Host-executor sort family (the TPU path's beyond-parity surface,
// dr_tpu/algorithms/sort.py, mirrored on the host executor so a
// vocabulary program runs identically on either; the reference ships
// no sort).  On shared memory the sample-sort's collective phases
// degenerate to one stable sort over the segment walk.
// NaN-aware strict weak order matching the TPU path's numpy contract
// (NaNs rank LAST ascending; plain operator< over NaNs is UB for
// std::stable_sort — round-5 review finding)
template <class T>
inline bool nan_less(const T& a, const T& b) {
  if constexpr (std::is_floating_point_v<T>) {
    bool na = std::isnan(a), nb = std::isnan(b);
    if (na || nb) return !na && nb;  // non-NaN < NaN
  }
  return a < b;
}

template <distributed_range R>
void sort(R&& r, bool descending = false) {
  using T = std::ranges::range_value_t<std::remove_cvref_t<R>>;
  std::vector<T> vals;
  for (auto&& s : drtpu::segments(r))
    for (auto& x : drtpu::local(s)) vals.push_back(x);
  std::stable_sort(vals.begin(), vals.end(), nan_less<T>);
  if (descending) std::reverse(vals.begin(), vals.end());
  std::size_t at = 0;
  for (auto&& s : drtpu::segments(r))
    for (auto& x : drtpu::local(s)) x = vals[at++];
}

template <distributed_range K, distributed_range V>
void sort_by_key(K&& keys, V&& values, bool descending = false) {
  using T = std::ranges::range_value_t<std::remove_cvref_t<K>>;
  using U = std::ranges::range_value_t<std::remove_cvref_t<V>>;
  std::vector<T> ks;
  std::vector<U> vs;
  for (auto&& s : drtpu::segments(keys))
    for (auto& x : drtpu::local(s)) ks.push_back(x);
  for (auto&& s : drtpu::segments(values))
    for (auto& x : drtpu::local(s)) vs.push_back(x);
  if (ks.size() != vs.size())
    throw std::invalid_argument(
        "sort_by_key: keys and values lengths differ");
  std::vector<std::size_t> order(ks.size());
  for (std::size_t i = 0; i < order.size(); ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [&](std::size_t a, std::size_t b) {
                     return nan_less(ks[a], ks[b]);
                   });
  if (descending) std::reverse(order.begin(), order.end());
  std::size_t at = 0;
  for (auto&& s : drtpu::segments(keys))
    for (auto& x : drtpu::local(s)) x = ks[order[at++]];
  at = 0;
  for (auto&& s : drtpu::segments(values))
    for (auto& x : drtpu::local(s)) x = vs[order[at++]];
}

template <distributed_range R>
bool is_sorted(R&& r) {
  bool have = false;
  std::ranges::range_value_t<std::remove_cvref_t<R>> prev{};
  for (auto&& s : drtpu::segments(r))
    for (auto& x : drtpu::local(s)) {
      if (have && nan_less(x, prev)) return false;  // NaNs rank last
      prev = x;
      have = true;
    }
  return true;
}

// per-segment scan + carried prefix (the reference's 3-phase scan,
// shp/algorithms/inclusive_scan.hpp:25-148, serialized on host)
template <distributed_range In, distributed_range Out,
          class Op = std::plus<>>
void inclusive_scan(In&& in, Out&& out, Op op = {}) {
  bool have_carry = false;
  std::ranges::range_value_t<std::remove_cvref_t<In>> carry{};
  if (drtpu::aligned(in, out)) {
    auto is = drtpu::local_segments(in);
    auto os = drtpu::local_segments(out);
    for (std::size_t k = 0; k < is.size(); ++k) {
      for (std::size_t i = 0; i < is[k].size(); ++i) {
        carry = have_carry ? op(carry, is[k][i]) : is[k][i];
        have_carry = true;
        os[k][i] = carry;
      }
    }
    return;
  }
  // misaligned fallback over the common prefix
  std::size_t n = std::min<std::size_t>(std::ranges::size(in),
                                        std::ranges::size(out));
  auto ib = std::ranges::begin(in);
  auto ob = std::ranges::begin(out);
  for (std::size_t i = 0; i < n; ++i, ++ib, ++ob) {
    carry = have_carry ? op(carry, *ib) : *ib;
    have_carry = true;
    *ob = carry;
  }
}

// exclusive variant (std::exclusive_scan surface; the reference spec
// names it, doc/spec/source/algorithms/)
template <distributed_range In, distributed_range Out, class T,
          class Op = std::plus<>>
void exclusive_scan(In&& in, Out&& out, T init, Op op = {}) {
  T carry = init;
  if (drtpu::aligned(in, out)) {
    auto is = drtpu::local_segments(in);
    auto os = drtpu::local_segments(out);
    for (std::size_t k = 0; k < is.size(); ++k) {
      for (std::size_t i = 0; i < is[k].size(); ++i) {
        T next = op(carry, is[k][i]);
        os[k][i] = carry;
        carry = next;
      }
    }
    return;
  }
  std::size_t n = std::min<std::size_t>(std::ranges::size(in),
                                        std::ranges::size(out));
  auto ib = std::ranges::begin(in);
  auto ob = std::ranges::begin(out);
  for (std::size_t i = 0; i < n; ++i, ++ib, ++ob) {
    T next = op(carry, *ib);
    *ob = carry;
    carry = next;
  }
}

}  // namespace drtpu
