// Native distributed_vector: 1-D block-distributed vector over a logical
// mesh of P ranks, with halo padding — the host-side model of the TPU
// layout (one padded row per shard; see dr_tpu/containers/
// distributed_vector.py, mirroring mhp dv.hpp:176-238).
//
// This is the native CPU executor of the vocabulary: segments are
// remote_span descriptors into per-rank buffers, halo exchange is
// neighbor copies over the same [ghost_prev | owned | ghost_next] layout
// the TPU backend uses (ppermute there, memcpy here), so a program written
// against the vocabulary runs identically on either executor.
#pragma once

#include <algorithm>
#include <cassert>
#include <cstddef>
#include <stdexcept>
#include <vector>

#include "iterator_adaptor.hpp"
#include "remote_span.hpp"
#include "segment_tools.hpp"
#include "vocabulary.hpp"

namespace drtpu {

struct halo_bounds {
  std::size_t prev = 0;
  std::size_t next = 0;
  bool periodic = false;
};

// Explicit per-rank block sizes (the reference's declared-but-unbuilt
// "// TODO: support teams, distributions", shp/distributed_vector.hpp:113;
// zero-size blocks = the "teams" case).  Mirrors the Python
// block_distribution (dr_tpu/containers/distribution.py).
struct block_distribution {
  std::vector<std::size_t> sizes;
  explicit block_distribution(std::vector<std::size_t> s)
      : sizes(std::move(s)) {}
};

enum class halo_op { second, plus, max, min, multiplies };

// The ghost->owner combine rule shared by span_halo and unstructured_halo
// (reference fold table, details/halo.hpp:92-110).
template <class T>
inline T halo_fold(halo_op op, T a, T b) {
  switch (op) {
    case halo_op::second: return b;
    case halo_op::plus: return a + b;
    case halo_op::max: return a > b ? a : b;
    case halo_op::min: return a < b ? a : b;
    case halo_op::multiplies: return a * b;
  }
  return b;
}

template <class T>
class distributed_vector;

// Ghost-cell controller (reference span_halo / halo_impl,
// details/halo.hpp:55-110,273-387).
template <class T>
class span_halo {
 public:
  explicit span_halo(distributed_vector<T>* dv) : dv_(dv) {}

  void exchange();
  void exchange_begin() { exchange(); }
  void exchange_finalize() {}
  void reduce(halo_op op);
  void reduce_plus() { reduce(halo_op::plus); }
  void reduce_max() { reduce(halo_op::max); }
  void reduce_min() { reduce(halo_op::min); }
  void reduce_multiplies() { reduce(halo_op::multiplies); }

 private:
  distributed_vector<T>* dv_;
};

// Accessor for the global iterator: (container, logical index) — the
// normal_distributed_iterator analog (details/
// normal_distributed_iterator.hpp:13-115) with O(1) indexing thanks to the
// uniform padded layout.
template <class T>
struct dv_accessor {
  using value_type = T;
  using difference_type = std::ptrdiff_t;

  distributed_vector<T>* dv = nullptr;
  std::size_t idx = 0;

  T& dereference() const { return (*dv)[idx]; }
  void operator+=(difference_type n) { idx += n; }
  bool operator==(const dv_accessor& o) const {
    return dv == o.dv && idx == o.idx;
  }
  auto operator<=>(const dv_accessor& o) const { return idx <=> o.idx; }
  difference_type distance_to(const dv_accessor& o) const {
    return static_cast<difference_type>(o.idx) -
           static_cast<difference_type>(idx);
  }
};

template <class T>
class distributed_vector {
 public:
  using value_type = T;
  using iterator = iterator_adaptor<dv_accessor<T>>;

  distributed_vector(std::size_t n, std::size_t nprocs,
                     halo_bounds hb = {})
      : n_(n), nprocs_(nprocs), hb_(hb), halo_(this) {
    assert(nprocs >= 1);
    // segment_size = max(ceil(n/p), prev, next)  (dv.hpp:190-193)
    seg_ = std::max({n ? (n + nprocs - 1) / nprocs : std::size_t{1},
                     hb.prev, hb.next, std::size_t{1}});
    init_uniform_windows();
    alloc_rows();
    if ((hb.prev || hb.next) && nprocs_ > 1) {
      std::size_t tail = n_ - (nprocs_ - 1) * seg_;
      if (n_ <= (nprocs_ - 1) * seg_)
        throw std::invalid_argument("halo requires nonempty shards");
      if (hb.periodic && tail < std::max(hb.prev, hb.next))
        throw std::invalid_argument("periodic halo: tail below radius");
    }
    // P == 1 periodic self-wrap: the single shard IS the ring tail, so
    // the same radius rule applies (n < radius would read pad cells —
    // round-5 native-fuzz finding; the Python container already
    // rejects this shape, parallel/halo.py generalized min-size checks)
    if ((hb.prev || hb.next) && hb.periodic && nprocs_ == 1 &&
        n_ < std::max(hb.prev, hb.next))
      throw std::invalid_argument("periodic halo: n below radius");
  }

  // Explicit distribution: rank r owns sizes[r] contiguous elements.
  // Halo padding requires the uniform layout (the exchange ring assumes
  // equal shards), matching the Python container's rule.
  distributed_vector(std::size_t n, std::size_t nprocs,
                     const block_distribution& dist, halo_bounds hb = {})
      : n_(n), nprocs_(nprocs), hb_(hb), halo_(this) {
    assert(nprocs >= 1);
    if (dist.sizes.size() != nprocs_)
      throw std::invalid_argument("distribution block count != nprocs");
    std::size_t total = 0;
    for (auto s : dist.sizes) total += s;
    if (total != n_)
      throw std::invalid_argument("distribution sizes do not sum to n");
    sizes_ = dist.sizes;
    starts_.resize(nprocs_);
    std::size_t acc = 0;
    std::size_t mx = 0;
    for (std::size_t r = 0; r < nprocs_; ++r) {
      starts_[r] = acc;
      acc += sizes_[r];
      mx = std::max(mx, sizes_[r]);
    }
    seg_ = std::max({mx, hb.prev, hb.next, std::size_t{1}});
    uniform_ = is_even_layout();
    if (!uniform_ && (hb.prev || hb.next))
      throw std::invalid_argument(
          "halo_bounds require the uniform block distribution");
    if ((hb.prev || hb.next) && nprocs_ > 1) {
      if (sizes_.back() == 0)
        throw std::invalid_argument("halo requires nonempty shards");
      if (hb.periodic && sizes_.back() < std::max(hb.prev, hb.next))
        throw std::invalid_argument("periodic halo: tail below radius");
    }
    alloc_rows();
  }

  // value semantics must re-seat the halo controller's back-pointer
  distributed_vector(const distributed_vector& o)
      : n_(o.n_), nprocs_(o.nprocs_), seg_(o.seg_), width_(o.width_),
        uniform_(o.uniform_), hb_(o.hb_), starts_(o.starts_),
        sizes_(o.sizes_), data_(o.data_), halo_(this) {}
  distributed_vector(distributed_vector&& o) noexcept
      : n_(o.n_), nprocs_(o.nprocs_), seg_(o.seg_), width_(o.width_),
        uniform_(o.uniform_), hb_(o.hb_), starts_(std::move(o.starts_)),
        sizes_(std::move(o.sizes_)), data_(std::move(o.data_)),
        halo_(this) {}
  distributed_vector& operator=(const distributed_vector& o) {
    n_ = o.n_; nprocs_ = o.nprocs_; seg_ = o.seg_; width_ = o.width_;
    uniform_ = o.uniform_; hb_ = o.hb_;
    starts_ = o.starts_; sizes_ = o.sizes_; data_ = o.data_;
    return *this;  // halo_ keeps pointing at *this
  }
  distributed_vector& operator=(distributed_vector&& o) noexcept {
    n_ = o.n_; nprocs_ = o.nprocs_; seg_ = o.seg_; width_ = o.width_;
    uniform_ = o.uniform_; hb_ = o.hb_;
    starts_ = std::move(o.starts_); sizes_ = std::move(o.sizes_);
    data_ = std::move(o.data_);
    return *this;
  }

  std::size_t size() const { return n_; }
  iterator begin() { return iterator(dv_accessor<T>{this, 0}); }
  iterator end() { return iterator(dv_accessor<T>{this, n_}); }
  std::size_t nprocs() const { return nprocs_; }
  std::size_t segment_size() const { return seg_; }
  bool uniform() const { return uniform_; }
  const std::vector<std::size_t>& block_sizes() const { return sizes_; }
  halo_bounds bounds() const { return hb_; }
  span_halo<T>& halo() { return halo_; }

  // rank owning logical index i
  std::size_t rank_of(std::size_t i) const {
    if (uniform_) return i / seg_;
    // last start <= i (upper_bound handles zero-size blocks: repeated
    // starts resolve to the last — owning — rank)
    auto it = std::upper_bound(starts_.begin(), starts_.end(), i);
    return static_cast<std::size_t>(it - starts_.begin()) - 1;
  }

  // element access through the padded layout
  T& operator[](std::size_t i) {
    std::size_t r = rank_of(i);
    return data_[r][hb_.prev + i - starts_[r]];
  }
  const T& operator[](std::size_t i) const {
    std::size_t r = rank_of(i);
    return data_[r][hb_.prev + i - starts_[r]];
  }

  // padded row of one shard (the TPU (nshards, width) row analog)
  std::span<T> shard_row(std::size_t r) {
    return {data_[r].data(), width_};
  }

  std::vector<remote_span<T>> dr_segments() {
    std::vector<remote_span<T>> segs;
    for (std::size_t r = 0; r < nprocs_; ++r) {
      if (!sizes_[r]) continue;
      segs.push_back(remote_span<T>(
          r, starts_[r],
          std::span<T>(data_[r].data() + hb_.prev, sizes_[r])));
    }
    return segs;
  }

  std::size_t valid_of(std::size_t r) const { return sizes_[r]; }

 private:
  friend class span_halo<T>;

  void init_uniform_windows() {
    starts_.resize(nprocs_);
    sizes_.resize(nprocs_);
    for (std::size_t r = 0; r < nprocs_; ++r) {
      starts_[r] = r * seg_;
      std::size_t end = std::min(n_, starts_[r] + seg_);
      sizes_[r] = end > starts_[r] ? end - starts_[r] : 0;
    }
    uniform_ = true;
  }

  bool is_even_layout() const {
    // explicit sizes matching what the DEFAULT ctor would build — i.e.
    // ceil-division windows under the halo-bumped segment size
    // (max(ceil(n/p), prev, next)) — so the fast div/mod indexing applies
    // and segments align with default-constructed peers
    std::size_t seg =
        std::max({n_ ? (n_ + nprocs_ - 1) / nprocs_ : std::size_t{1},
                  hb_.prev, hb_.next, std::size_t{1}});
    if (seg_ != seg) return false;  // rank_of divides by seg_; must agree
    for (std::size_t r = 0; r < nprocs_; ++r) {
      std::size_t begin = std::min(n_, r * seg);
      std::size_t end = std::min(n_, begin + seg);
      if (starts_[r] != r * seg && sizes_[r] != 0) return false;
      if (sizes_[r] != end - begin) return false;
    }
    return true;
  }

  void alloc_rows() {
    width_ = hb_.prev + seg_ + hb_.next;
    data_.assign(nprocs_, {});
    for (auto& row : data_) row.assign(width_, T{});
  }

  std::size_t n_, nprocs_, seg_ = 1, width_ = 1;
  bool uniform_ = false;
  halo_bounds hb_;
  std::vector<std::size_t> starts_, sizes_;
  std::vector<std::vector<T>> data_;
  span_halo<T> halo_;
};

template <class T>
void span_halo<T>::exchange() {
  auto& dv = *dv_;
  auto [prev, next, periodic] = dv.hb_;
  std::size_t P = dv.nprocs_;
  if ((!prev && !next) || (P == 1 && !periodic)) return;
  for (std::size_t r = 0; r < P; ++r) {
    std::size_t valid = dv.valid_of(r);
    if (!valid) continue;
    // ghost_prev of r  <-  last `prev` valid cells of r-1 (fwd shift)
    if (prev && (r > 0 || periodic)) {
      std::size_t src = (r + P - 1) % P;
      std::size_t sv = dv.valid_of(src);
      std::copy_n(dv.data_[src].data() + prev + sv - prev, prev,
                  dv.data_[r].data());
    }
    // ghost_next of r (right after valid tail) <- first `next` of r+1
    if (next && (r + 1 < P || periodic)) {
      std::size_t src = (r + 1) % P;
      std::copy_n(dv.data_[src].data() + prev, next,
                  dv.data_[r].data() + prev + valid);
    }
  }
}

template <class T>
void span_halo<T>::reduce(halo_op op) {
  auto& dv = *dv_;
  auto [prev, next, periodic] = dv.hb_;
  std::size_t P = dv.nprocs_;
  if ((!prev && !next) || (P == 1 && !periodic)) return;
  auto fold = [op](T a, T b) -> T { return halo_fold(op, a, b); };
  // ghosts fold back into their owners (halo.hpp:73-110)
  for (std::size_t r = 0; r < P; ++r) {
    std::size_t valid = dv.valid_of(r);
    if (!valid) continue;
    if (prev && (r > 0 || periodic)) {
      std::size_t owner = (r + P - 1) % P;
      std::size_t ov = dv.valid_of(owner);
      T* dst = dv.data_[owner].data() + prev + ov - prev;
      const T* src = dv.data_[r].data();
      for (std::size_t k = 0; k < prev; ++k) dst[k] = fold(dst[k], src[k]);
    }
    if (next && (r + 1 < P || periodic)) {
      std::size_t owner = (r + 1) % P;
      T* dst = dv.data_[owner].data() + prev;
      const T* src = dv.data_[r].data() + prev + valid;
      for (std::size_t k = 0; k < next; ++k) dst[k] = fold(dst[k], src[k]);
    }
  }
}

static_assert(distributed_range<distributed_vector<double>&>);

}  // namespace drtpu
