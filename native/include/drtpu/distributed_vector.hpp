// Native distributed_vector: 1-D block-distributed vector over a logical
// mesh of P ranks, with halo padding — the host-side model of the TPU
// layout (one padded row per shard; see dr_tpu/containers/
// distributed_vector.py, mirroring mhp dv.hpp:176-238).
//
// This is the native CPU executor of the vocabulary: segments are
// remote_span descriptors into per-rank buffers, halo exchange is
// neighbor copies over the same [ghost_prev | owned | ghost_next] layout
// the TPU backend uses (ppermute there, memcpy here), so a program written
// against the vocabulary runs identically on either executor.
#pragma once

#include <algorithm>
#include <cassert>
#include <cstddef>
#include <stdexcept>
#include <vector>

#include "iterator_adaptor.hpp"
#include "remote_span.hpp"
#include "segment_tools.hpp"
#include "vocabulary.hpp"

namespace drtpu {

struct halo_bounds {
  std::size_t prev = 0;
  std::size_t next = 0;
  bool periodic = false;
};

enum class halo_op { second, plus, max, min, multiplies };

template <class T>
class distributed_vector;

// Ghost-cell controller (reference span_halo / halo_impl,
// details/halo.hpp:55-110,273-387).
template <class T>
class span_halo {
 public:
  explicit span_halo(distributed_vector<T>* dv) : dv_(dv) {}

  void exchange();
  void exchange_begin() { exchange(); }
  void exchange_finalize() {}
  void reduce(halo_op op);
  void reduce_plus() { reduce(halo_op::plus); }
  void reduce_max() { reduce(halo_op::max); }
  void reduce_min() { reduce(halo_op::min); }
  void reduce_multiplies() { reduce(halo_op::multiplies); }

 private:
  distributed_vector<T>* dv_;
};

// Accessor for the global iterator: (container, logical index) — the
// normal_distributed_iterator analog (details/
// normal_distributed_iterator.hpp:13-115) with O(1) indexing thanks to the
// uniform padded layout.
template <class T>
struct dv_accessor {
  using value_type = T;
  using difference_type = std::ptrdiff_t;

  distributed_vector<T>* dv = nullptr;
  std::size_t idx = 0;

  T& dereference() const { return (*dv)[idx]; }
  void operator+=(difference_type n) { idx += n; }
  bool operator==(const dv_accessor& o) const {
    return dv == o.dv && idx == o.idx;
  }
  auto operator<=>(const dv_accessor& o) const { return idx <=> o.idx; }
  difference_type distance_to(const dv_accessor& o) const {
    return static_cast<difference_type>(o.idx) -
           static_cast<difference_type>(idx);
  }
};

template <class T>
class distributed_vector {
 public:
  using value_type = T;
  using iterator = iterator_adaptor<dv_accessor<T>>;

  distributed_vector(std::size_t n, std::size_t nprocs,
                     halo_bounds hb = {})
      : n_(n), nprocs_(nprocs), hb_(hb), halo_(this) {
    assert(nprocs >= 1);
    // segment_size = max(ceil(n/p), prev, next)  (dv.hpp:190-193)
    seg_ = std::max({n ? (n + nprocs - 1) / nprocs : std::size_t{1},
                     hb.prev, hb.next, std::size_t{1}});
    width_ = hb.prev + seg_ + hb.next;
    data_.assign(nprocs_, {});
    for (auto& row : data_) row.assign(width_, T{});
    if ((hb.prev || hb.next) && nprocs_ > 1) {
      std::size_t tail = n_ - (nprocs_ - 1) * seg_;
      if (n_ <= (nprocs_ - 1) * seg_)
        throw std::invalid_argument("halo requires nonempty shards");
      if (hb.periodic && tail < std::max(hb.prev, hb.next))
        throw std::invalid_argument("periodic halo: tail below radius");
    }
  }

  // value semantics must re-seat the halo controller's back-pointer
  distributed_vector(const distributed_vector& o)
      : n_(o.n_), nprocs_(o.nprocs_), seg_(o.seg_), width_(o.width_),
        hb_(o.hb_), data_(o.data_), halo_(this) {}
  distributed_vector(distributed_vector&& o) noexcept
      : n_(o.n_), nprocs_(o.nprocs_), seg_(o.seg_), width_(o.width_),
        hb_(o.hb_), data_(std::move(o.data_)), halo_(this) {}
  distributed_vector& operator=(const distributed_vector& o) {
    n_ = o.n_; nprocs_ = o.nprocs_; seg_ = o.seg_; width_ = o.width_;
    hb_ = o.hb_; data_ = o.data_;
    return *this;  // halo_ keeps pointing at *this
  }
  distributed_vector& operator=(distributed_vector&& o) noexcept {
    n_ = o.n_; nprocs_ = o.nprocs_; seg_ = o.seg_; width_ = o.width_;
    hb_ = o.hb_; data_ = std::move(o.data_);
    return *this;
  }

  std::size_t size() const { return n_; }
  iterator begin() { return iterator(dv_accessor<T>{this, 0}); }
  iterator end() { return iterator(dv_accessor<T>{this, n_}); }
  std::size_t nprocs() const { return nprocs_; }
  std::size_t segment_size() const { return seg_; }
  halo_bounds bounds() const { return hb_; }
  span_halo<T>& halo() { return halo_; }

  // element access through the padded layout
  T& operator[](std::size_t i) {
    return data_[i / seg_][hb_.prev + i % seg_];
  }
  const T& operator[](std::size_t i) const {
    return data_[i / seg_][hb_.prev + i % seg_];
  }

  // padded row of one shard (the TPU (nshards, width) row analog)
  std::span<T> shard_row(std::size_t r) {
    return {data_[r].data(), width_};
  }

  std::vector<remote_span<T>> dr_segments() {
    std::vector<remote_span<T>> segs;
    for (std::size_t r = 0; r < nprocs_; ++r) {
      std::size_t begin = r * seg_;
      std::size_t end = std::min(n_, begin + seg_);
      if (begin >= end) break;
      segs.push_back(remote_span<T>(
          r, begin,
          std::span<T>(data_[r].data() + hb_.prev, end - begin)));
    }
    return segs;
  }

  std::size_t valid_of(std::size_t r) const {
    std::size_t begin = r * seg_;
    std::size_t end = std::min(n_, begin + seg_);
    return end > begin ? end - begin : 0;
  }

 private:
  friend class span_halo<T>;
  std::size_t n_, nprocs_, seg_, width_;
  halo_bounds hb_;
  std::vector<std::vector<T>> data_;
  span_halo<T> halo_;
};

template <class T>
void span_halo<T>::exchange() {
  auto& dv = *dv_;
  auto [prev, next, periodic] = dv.hb_;
  std::size_t P = dv.nprocs_;
  if ((!prev && !next) || (P == 1 && !periodic)) return;
  for (std::size_t r = 0; r < P; ++r) {
    std::size_t valid = dv.valid_of(r);
    if (!valid) continue;
    // ghost_prev of r  <-  last `prev` valid cells of r-1 (fwd shift)
    if (prev && (r > 0 || periodic)) {
      std::size_t src = (r + P - 1) % P;
      std::size_t sv = dv.valid_of(src);
      std::copy_n(dv.data_[src].data() + prev + sv - prev, prev,
                  dv.data_[r].data());
    }
    // ghost_next of r (right after valid tail) <- first `next` of r+1
    if (next && (r + 1 < P || periodic)) {
      std::size_t src = (r + 1) % P;
      std::copy_n(dv.data_[src].data() + prev, next,
                  dv.data_[r].data() + prev + valid);
    }
  }
}

template <class T>
void span_halo<T>::reduce(halo_op op) {
  auto& dv = *dv_;
  auto [prev, next, periodic] = dv.hb_;
  std::size_t P = dv.nprocs_;
  if ((!prev && !next) || (P == 1 && !periodic)) return;
  auto fold = [op](T a, T b) -> T {
    switch (op) {
      case halo_op::second: return b;
      case halo_op::plus: return a + b;
      case halo_op::max: return a > b ? a : b;
      case halo_op::min: return a < b ? a : b;
      case halo_op::multiplies: return a * b;
    }
    return b;
  };
  // ghosts fold back into their owners (halo.hpp:73-110)
  for (std::size_t r = 0; r < P; ++r) {
    std::size_t valid = dv.valid_of(r);
    if (!valid) continue;
    if (prev && (r > 0 || periodic)) {
      std::size_t owner = (r + P - 1) % P;
      std::size_t ov = dv.valid_of(owner);
      T* dst = dv.data_[owner].data() + prev + ov - prev;
      const T* src = dv.data_[r].data();
      for (std::size_t k = 0; k < prev; ++k) dst[k] = fold(dst[k], src[k]);
    }
    if (next && (r + 1 < P || periodic)) {
      std::size_t owner = (r + 1) % P;
      T* dst = dv.data_[owner].data() + prev;
      const T* src = dv.data_[r].data() + prev + valid;
      for (std::size_t k = 0; k < next; ++k) dst[k] = fold(dst[k], src[k]);
    }
  }
}

static_assert(distributed_range<distributed_vector<double>&>);

}  // namespace drtpu
