// Native unstructured (index-list) halo over the host executor.
//
// Reference: `index_group` / `unstructured_halo`
// (include/dr/details/halo.hpp:148-271): per neighbor rank, an index
// list into the local data; exchange packs owned values through the
// index arrays into messages and unpacks into ghosts; reduce reverses
// direction and folds with an op.  The contiguity optimization
// (halo.hpp:161-166: unbuffered send straight from &data[indices[0]])
// becomes irrelevant in shared memory — every transfer is a direct
// indexed copy.
//
// Surface mirrors the TPU-side dr_tpu/parallel/unstructured_halo.py:
// construct from a distributed_vector plus {rank: [global indices]}
// ghost maps; exchange() refreshes ghosts from owners (one gather);
// reduce(op) folds ghost contributions back into owners.
#pragma once

#include <cstddef>
#include <map>
#include <span>
#include <stdexcept>
#include <vector>

#include "distributed_vector.hpp"

namespace drtpu {

template <class T>
class unstructured_halo {
 public:
  // ghost_indices[r] = the GLOBAL element indices rank r mirrors.
  unstructured_halo(distributed_vector<T>& dv,
                    const std::map<std::size_t, std::vector<std::size_t>>&
                        ghost_indices)
      : dv_(&dv) {
    // one flat ghost buffer carved per rank (halo.hpp:27-51)
    for (auto& [rank, indices] : ghost_indices) {
      if (rank >= dv.nprocs())
        throw std::invalid_argument("unstructured_halo: rank out of range");
      if (indices.empty()) continue;
      for (auto i : indices)
        if (i >= dv.size())
          throw std::invalid_argument(
              "unstructured_halo: index out of range");
      offsets_[rank] = {flat_.size(), flat_.size() + indices.size()};
      flat_.insert(flat_.end(), indices.begin(), indices.end());
    }
    ghost_.assign(flat_.size(), T{});
  }

  // owner -> ghost: refresh every mirrored value (halo.hpp:55-70).
  void exchange() {
    auto& dv = *dv_;
    for (std::size_t k = 0; k < flat_.size(); ++k) ghost_[k] = dv[flat_[k]];
  }
  void exchange_begin() { exchange(); }
  void exchange_finalize() {}

  std::span<T> ghost_values(std::size_t rank) {
    auto it = offsets_.find(rank);
    if (it == offsets_.end()) return {};
    auto [a, b] = it->second;
    return {ghost_.data() + a, b - a};
  }

  void set_ghost_values(std::size_t rank, std::span<const T> values) {
    auto it = offsets_.find(rank);
    if (it == offsets_.end() || values.size() != it->second.second -
                                                     it->second.first)
      throw std::invalid_argument("set_ghost_values: bad rank or size");
    std::copy(values.begin(), values.end(),
              ghost_.begin() +
                  static_cast<std::ptrdiff_t>(it->second.first));
  }

  // ghost -> owner: fold contributions back (halo.hpp:73-110).  Unlike
  // exchange, duplicates fold sequentially in flat order (the reference's
  // unpack loop semantics).
  void reduce(halo_op op) {
    auto& dv = *dv_;
    for (std::size_t k = 0; k < flat_.size(); ++k) {
      T& dst = dv[flat_[k]];
      dst = halo_fold(op, dst, ghost_[k]);
    }
  }
  void reduce_begin(halo_op op) { reduce(op); }
  void reduce_finalize() {}
  void reduce_plus() { reduce(halo_op::plus); }
  void reduce_max() { reduce(halo_op::max); }
  void reduce_min() { reduce(halo_op::min); }
  void reduce_multiplies() { reduce(halo_op::multiplies); }

 private:
  distributed_vector<T>* dv_;
  std::vector<std::size_t> flat_;
  std::vector<T> ghost_;
  std::map<std::size_t, std::pair<std::size_t, std::size_t>> offsets_;
};

}  // namespace drtpu
