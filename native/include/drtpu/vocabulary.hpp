// drtpu native vocabulary: rank / segments / local customization points and
// the remote/distributed range concepts.
//
// C++20 re-design of the reference's L0 layer (include/dr/details/
// ranges.hpp:38-161, include/dr/concepts/concepts.hpp:11-53) for the TPU
// execution model: a "rank" is a mesh slot (device position), segments()
// yields per-shard descriptors, and local() yields the host-visible span of
// a shard's staged buffer.  Resolution order mirrors the reference: member
// function, then ADL hook (dr_rank/dr_segments/dr_local), then fallback.
#pragma once

#include <concepts>
#include <cstddef>
#include <iterator>
#include <ranges>
#include <type_traits>
#include <utility>

namespace drtpu {

// --------------------------------------------------------------------------
// rank
// --------------------------------------------------------------------------
namespace cpo_detail {

template <class T>
concept member_rank = requires(T&& t) {
  { std::forward<T>(t).dr_rank() } -> std::convertible_to<std::size_t>;
};

template <class T>
concept adl_rank = requires(T&& t) {
  { dr_rank(std::forward<T>(t)) } -> std::convertible_to<std::size_t>;
};

struct rank_fn {
  template <class T>
    requires member_rank<T> || adl_rank<T>
  constexpr std::size_t operator()(T&& t) const {
    if constexpr (member_rank<T>)
      return std::forward<T>(t).dr_rank();
    else
      return dr_rank(std::forward<T>(t));
  }
};

// --------------------------------------------------------------------------
// segments
// --------------------------------------------------------------------------
template <class T>
concept member_segments = requires(T&& t) {
  { std::forward<T>(t).dr_segments() } -> std::ranges::forward_range;
};

template <class T>
concept adl_segments = requires(T&& t) {
  { dr_segments(std::forward<T>(t)) } -> std::ranges::forward_range;
};

struct segments_fn {
  template <class T>
    requires member_segments<T> || adl_segments<T>
  constexpr decltype(auto) operator()(T&& t) const {
    if constexpr (member_segments<T>)
      return std::forward<T>(t).dr_segments();
    else
      return dr_segments(std::forward<T>(t));
  }
};

// --------------------------------------------------------------------------
// local
// --------------------------------------------------------------------------
template <class T>
concept member_local = requires(T&& t) {
  std::forward<T>(t).dr_local();
};

template <class T>
concept adl_local = requires(T&& t) {
  dr_local(std::forward<T>(t));
};

struct local_fn {
  template <class T>
    requires member_local<T> || adl_local<T> || std::contiguous_iterator<std::remove_cvref_t<T>>
  constexpr auto operator()(T&& t) const {
    if constexpr (member_local<T>)
      return std::forward<T>(t).dr_local();
    else if constexpr (adl_local<T>)
      return dr_local(std::forward<T>(t));
    else
      // contiguous iterators are already local (ranges.hpp:150-155)
      return std::remove_cvref_t<T>(std::forward<T>(t));
  }
};

}  // namespace cpo_detail

inline constexpr cpo_detail::rank_fn rank{};
inline constexpr cpo_detail::segments_fn segments{};
inline constexpr cpo_detail::local_fn local{};

// --------------------------------------------------------------------------
// concepts (concepts.hpp:11-53 equivalents)
// --------------------------------------------------------------------------

template <class I>
concept remote_iterator =
    std::forward_iterator<I> && requires(I i) { drtpu::rank(i); };

template <class R>
concept remote_range =
    std::ranges::sized_range<R> && requires(R&& r) { drtpu::rank(r); };

template <class R>
concept distributed_range =
    std::ranges::sized_range<R> && requires(R&& r) { drtpu::segments(r); };

template <class I>
concept remote_contiguous_iterator =
    remote_iterator<I> && requires(I i) {
      { drtpu::local(i) } -> std::contiguous_iterator;
    };

template <class R>
concept remote_contiguous_range =
    remote_range<R> && requires(R&& r) {
      { drtpu::local(std::ranges::begin(r)) } -> std::contiguous_iterator;
    };

template <class I>
concept distributed_iterator =
    std::forward_iterator<I> && requires(I i) { drtpu::segments(i); };

template <class R>
concept distributed_contiguous_range =
    distributed_range<R> &&
    requires(R&& r) {
      requires remote_contiguous_range<
          std::ranges::range_value_t<decltype(drtpu::segments(r))>>;
    };

}  // namespace drtpu
