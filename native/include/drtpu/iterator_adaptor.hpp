// iterator_adaptor: build a full random-access iterator from a small
// "accessor" (state + advance/compare/dereference) — native equivalent of
// the reference's lib::iterator_adaptor (details/iterator_adaptor.hpp:
// 18-193), which every custom iterator there is built on.  The accessor
// contract here: value_type, difference_type, operator+=(difference),
// operator==(const A&), operator<=>(const A&), dereference() -> reference.
#pragma once

#include <compare>
#include <cstddef>
#include <iterator>

namespace drtpu {

template <class Accessor>
class iterator_adaptor {
 public:
  using accessor_type = Accessor;
  using value_type = typename Accessor::value_type;
  using difference_type = typename Accessor::difference_type;
  using reference = decltype(std::declval<const Accessor&>().dereference());
  using iterator_category = std::random_access_iterator_tag;

  iterator_adaptor() = default;
  explicit iterator_adaptor(Accessor acc) : acc_(acc) {}
  template <class... Args>
    requires std::constructible_from<Accessor, Args...> &&
             (sizeof...(Args) > 0)
  explicit iterator_adaptor(Args&&... args)
      : acc_(std::forward<Args>(args)...) {}

  reference operator*() const { return acc_.dereference(); }
  reference operator[](difference_type n) const {
    auto t = acc_;
    t += n;
    return t.dereference();
  }

  iterator_adaptor& operator+=(difference_type n) {
    acc_ += n;
    return *this;
  }
  iterator_adaptor& operator-=(difference_type n) { return *this += -n; }
  iterator_adaptor& operator++() { return *this += 1; }
  iterator_adaptor operator++(int) {
    auto t = *this;
    ++*this;
    return t;
  }
  iterator_adaptor& operator--() { return *this += -1; }
  iterator_adaptor operator--(int) {
    auto t = *this;
    --*this;
    return t;
  }

  friend iterator_adaptor operator+(iterator_adaptor it, difference_type n) {
    return it += n;
  }
  friend iterator_adaptor operator+(difference_type n, iterator_adaptor it) {
    return it += n;
  }
  friend iterator_adaptor operator-(iterator_adaptor it, difference_type n) {
    return it += -n;
  }
  friend difference_type operator-(const iterator_adaptor& a,
                                   const iterator_adaptor& b) {
    return a.acc_.distance_to(b.acc_) * -1;
  }

  friend bool operator==(const iterator_adaptor& a,
                         const iterator_adaptor& b) {
    return a.acc_ == b.acc_;
  }
  friend auto operator<=>(const iterator_adaptor& a,
                          const iterator_adaptor& b) {
    return a.acc_ <=> b.acc_;
  }

  const Accessor& accessor() const { return acc_; }

 private:
  Accessor acc_;
};

}  // namespace drtpu
