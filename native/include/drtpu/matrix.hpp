// Tiled distributed matrices on the host executor: block-cyclic partitions,
// dense matrices, CSR sparse matrices, and gemv/gemm.
//
// Native equivalents of the reference's SHP matrix stack —
// `matrix_partition`/`block_cyclic` with near-square grid factorization
// (shp/containers/matrix_partition.hpp:23-86, detail.hpp:15-24),
// `dense_matrix` (one tile per grid cell placed by tile_rank,
// dense_matrix.hpp:245-263), `sparse_matrix` (per-tile CSR triples,
// sparse_matrix.hpp:344-349), and `gemv` (row-tiled SpMV with replicated b,
// gemv.hpp:45-66).  Re-designed for value-descriptor segments: a tile is a
// `matrix_tile` descriptor (rank, global offsets, shape, leading dimension,
// host span) — the same tiled layout the TPU path shards over a 2-D mesh
// view (dr_tpu/containers/dense_matrix.py).
#pragma once

#include <cassert>
#include <cstddef>
#include <memory>
#include <span>
#include <vector>

#include "vocabulary.hpp"

namespace drtpu {

struct index2d {
  std::size_t i = 0, j = 0;
  bool operator==(const index2d&) const = default;
};

// Near-square factorization of p (shp/containers/detail.hpp:15-24).
inline index2d factor_grid(std::size_t p) {
  std::size_t a = 1;
  for (std::size_t d = 1; d * d <= p; ++d)
    if (p % d == 0) a = d;
  return {p / a, a};
}

// Block-cyclic placement: tile (ti, tj) lives on rank
// grid[(ti % gi) * gj + (tj % gj)]  (matrix_partition.hpp:34-63).
class block_cyclic {
 public:
  explicit block_cyclic(index2d grid) : grid_(grid) {}
  explicit block_cyclic(std::size_t nprocs) : grid_(factor_grid(nprocs)) {}

  index2d grid_shape() const { return grid_; }
  std::size_t tile_rank(index2d tile) const {
    return (tile.i % grid_.i) * grid_.j + (tile.j % grid_.j);
  }

 private:
  index2d grid_;
};

// row_tiles: 1-D row-stripe partition (grid (p, 1)) — the layout the
// reference's gemv asserts (gemv.hpp:21 grid_shape[1]==1).
inline block_cyclic row_tiles(std::size_t nprocs) {
  return block_cyclic(index2d{nprocs, 1});
}

// One dense tile: (rank, global row/col origin, shape, leading dim, data).
template <class T>
class matrix_tile {
 public:
  matrix_tile() = default;
  matrix_tile(std::size_t rank, index2d origin, index2d shape,
              std::size_t ld, T* data)
      : rank_(rank), origin_(origin), shape_(shape), ld_(ld), data_(data) {}

  std::size_t dr_rank() const { return rank_; }
  std::span<T> dr_local() const {
    return {data_, (shape_.i - 1) * ld_ + shape_.j};
  }
  index2d origin() const { return origin_; }
  index2d shape() const { return shape_; }
  std::size_t ld() const { return ld_; }
  std::size_t size() const { return shape_.i * shape_.j; }
  bool empty() const { return size() == 0; }

  T& operator()(std::size_t i, std::size_t j) const {
    return data_[i * ld_ + j];
  }
  // row-slice of the tile (dense_matrix_view row slicing surface)
  std::span<T> row(std::size_t i) const { return {data_ + i * ld_, shape_.j}; }

 private:
  std::size_t rank_ = 0;
  index2d origin_{}, shape_{};
  std::size_t ld_ = 0;
  T* data_ = nullptr;
};

template <class T>
class dense_matrix {
 public:
  using value_type = T;

  dense_matrix(index2d shape, index2d tile_shape, block_cyclic part)
      : shape_(shape), tshape_(tile_shape), part_(part) {
    assert(tshape_.i && tshape_.j);
    grid_ = {ceil_div(shape_.i, tshape_.i), ceil_div(shape_.j, tshape_.j)};
    tiles_.resize(grid_.i * grid_.j);
    for (std::size_t ti = 0; ti < grid_.i; ++ti)
      for (std::size_t tj = 0; tj < grid_.j; ++tj)
        tiles_[ti * grid_.j + tj].assign(
            tile_rows(ti) * tile_cols(tj), T{});
  }

  // default: near-square grid over nprocs, one tile per grid cell
  // (`tile::div` auto-tiling, matrix_partition.hpp:64-86)
  dense_matrix(index2d shape, std::size_t nprocs)
      : dense_matrix(shape,
                     index2d{ceil_div(shape.i, factor_grid(nprocs).i),
                             ceil_div(shape.j, factor_grid(nprocs).j)},
                     block_cyclic(nprocs)) {}

  index2d shape() const { return shape_; }
  index2d grid_shape() const { return grid_; }
  index2d tile_shape() const { return tshape_; }
  std::size_t size() const { return shape_.i * shape_.j; }

  std::size_t tile_rows(std::size_t ti) const {
    return std::min(tshape_.i, shape_.i - ti * tshape_.i);
  }
  std::size_t tile_cols(std::size_t tj) const {
    return std::min(tshape_.j, shape_.j - tj * tshape_.j);
  }

  matrix_tile<T> tile(index2d t) {
    auto& buf = tiles_[t.i * grid_.j + t.j];
    return {part_.tile_rank(t),
            {t.i * tshape_.i, t.j * tshape_.j},
            {tile_rows(t.i), tile_cols(t.j)},
            tile_cols(t.j),
            buf.data()};
  }

  std::vector<matrix_tile<T>> dr_segments() {
    std::vector<matrix_tile<T>> out;
    out.reserve(tiles_.size());
    for (std::size_t ti = 0; ti < grid_.i; ++ti)
      for (std::size_t tj = 0; tj < grid_.j; ++tj)
        out.push_back(tile({ti, tj}));
    return out;
  }

  T& operator()(std::size_t i, std::size_t j) {
    index2d t{i / tshape_.i, j / tshape_.j};
    return tile(t)(i % tshape_.i, j % tshape_.j);
  }

 private:
  static std::size_t ceil_div(std::size_t a, std::size_t b) {
    return (a + b - 1) / b;
  }

  index2d shape_, tshape_, grid_{};
  block_cyclic part_;
  std::vector<std::vector<T>> tiles_;
};

// --------------------------------------------------------------------------
// CSR sparse matrix, row-striped (one CSR triple per row tile)
// --------------------------------------------------------------------------

template <class T, class I = std::size_t>
struct csr_tile {
  std::size_t rank = 0;
  std::size_t row_origin = 0;
  std::size_t col_origin = 0;  // 2-D grids; 0 for row stripes
  index2d shape{};
  std::vector<T> values;
  std::vector<I> rowptr;  // shape.i + 1 entries
  std::vector<I> colind;  // tile-local when col_origin > 0

  std::size_t dr_rank() const { return rank; }
  std::size_t nnz() const { return values.size(); }
};

template <class T, class I = std::size_t>
class sparse_matrix {
 public:
  using value_type = T;

  // Row-striped build from COO triplets (grid {nprocs, 1}).
  sparse_matrix(index2d shape, std::size_t nprocs,
                const std::vector<std::tuple<std::size_t, std::size_t, T>>&
                    entries)
      : sparse_matrix(shape, index2d{nprocs, 1}, entries) {}

  // 2-D tile grid (sparse_matrix.hpp:344-349 partitions sparse through
  // the same matrix_partition machinery as dense; the Python side's
  // psum-over-mesh-columns SpMV mirrors this layout).  Tiles hold
  // LOCAL column indices with a col_origin when the grid has columns.
  sparse_matrix(index2d shape, index2d grid,
                const std::vector<std::tuple<std::size_t, std::size_t, T>>&
                    entries)
      : shape_(shape), grid_(grid), nprocs_(grid.i * grid.j) {
    assert(grid.i && grid.j);
    stripe_ = std::max<std::size_t>((shape.i + grid.i - 1) / grid.i, 1);
    cstripe_ = std::max<std::size_t>((shape.j + grid.j - 1) / grid.j, 1);
    tiles_.resize(nprocs_);
    for (std::size_t r = 0; r < nprocs_; ++r) {
      auto& t = tiles_[r];
      t.rank = r;
      t.row_origin = (r / grid.j) * stripe_;
      t.col_origin = grid.j > 1 ? (r % grid.j) * cstripe_ : 0;
      std::size_t rows = t.row_origin < shape.i
                             ? std::min(stripe_, shape.i - t.row_origin)
                             : 0;
      std::size_t cols =
          grid.j > 1 ? (t.col_origin < shape.j
                            ? std::min(cstripe_, shape.j - t.col_origin)
                            : 0)
                     : shape.j;
      t.shape = {rows, cols};
      t.rowptr.assign(rows + 1, 0);
    }
    auto tile_of = [&](std::size_t i, std::size_t j) {
      return (i / stripe_) * grid_.j + (grid_.j > 1 ? j / cstripe_ : 0);
    };
    // counting sort by (tile, local row)
    for (auto& [i, j, v] : entries) {
      auto& t = tiles_[tile_of(i, j)];
      ++t.rowptr[i - t.row_origin + 1];
    }
    for (auto& t : tiles_) {
      for (std::size_t k = 1; k < t.rowptr.size(); ++k)
        t.rowptr[k] += t.rowptr[k - 1];
      t.values.resize(t.rowptr.back());
      t.colind.resize(t.rowptr.back());
    }
    std::vector<std::vector<I>> cursor(nprocs_);
    for (std::size_t r = 0; r < nprocs_; ++r)
      cursor[r].assign(tiles_[r].rowptr.begin(), tiles_[r].rowptr.end());
    for (auto& [i, j, v] : entries) {
      auto r = tile_of(i, j);
      auto& t = tiles_[r];
      I& c = cursor[r][i - t.row_origin];
      t.values[c] = v;
      t.colind[c] = static_cast<I>(j - t.col_origin);
      ++c;
    }
  }

  index2d shape() const { return shape_; }
  index2d grid_shape() const { return grid_; }
  std::size_t nnz() const {
    std::size_t s = 0;
    for (auto& t : tiles_) s += t.nnz();
    return s;
  }
  std::size_t stripe() const { return stripe_; }
  std::size_t col_stripe() const { return cstripe_; }
  const std::vector<csr_tile<T, I>>& tiles() const { return tiles_; }
  const csr_tile<T, I>& tile(std::size_t r) const { return tiles_[r]; }

 private:
  index2d shape_;
  index2d grid_{1, 1};
  std::size_t nprocs_, stripe_ = 1, cstripe_ = 1;
  std::vector<csr_tile<T, I>> tiles_;
};

// --------------------------------------------------------------------------
// gemv / gemm
// --------------------------------------------------------------------------

// SpMV c += A * b, row-striped A; b replicated to every tile's executor
// (the reference's replicated-b design, gemv.hpp:39-66) — on the host
// executor replication is free, the accumulation contract is identical.
template <class T, class I, class VecC, class VecB>
void gemv(VecC&& c, const sparse_matrix<T, I>& a, const VecB& b) {
  assert(std::ranges::size(b) >= a.shape().j);
  for (auto& t : a.tiles()) {
    for (std::size_t li = 0; li < t.shape.i; ++li) {
      T acc{};
      for (I k = t.rowptr[li]; k < t.rowptr[li + 1]; ++k)
        acc += t.values[k] * b[t.col_origin + t.colind[k]];
      c[t.row_origin + li] += acc;  // per-tile partials accumulate
    }
  }
}

// Dense gemv over tiled A.
template <class T, class VecC, class VecB>
void gemv(VecC&& c, dense_matrix<T>& a, const VecB& b) {
  for (auto& t : a.dr_segments()) {
    for (std::size_t li = 0; li < t.shape().i; ++li) {
      T acc{};
      for (std::size_t lj = 0; lj < t.shape().j; ++lj)
        acc += t(li, lj) * b[t.origin().j + lj];
      c[t.origin().i + li] += acc;
    }
  }
}

// Dense C += A * B over tiles (the SUMMA traversal: every (Ci, k, Bj)
// tile triple with a non-empty global-range intersection contributes; on
// the TPU path this is the 2-D mesh matmul).  All element access goes
// through tile-local spans — tilings of A, B, C need not match.
template <class T>
void gemm(dense_matrix<T>& c, dense_matrix<T>& a, dense_matrix<T>& b) {
  assert(a.shape().j == b.shape().i);
  assert(c.shape().i == a.shape().i && c.shape().j == b.shape().j);
  auto a_tiles = a.dr_segments();
  auto b_tiles = b.dr_segments();
  for (auto& ct : c.dr_segments()) {
    std::size_t ci0 = ct.origin().i, ci1 = ci0 + ct.shape().i;
    std::size_t cj0 = ct.origin().j, cj1 = cj0 + ct.shape().j;
    for (auto& at : a_tiles) {
      std::size_t i0 = std::max(ci0, at.origin().i);
      std::size_t i1 = std::min(ci1, at.origin().i + at.shape().i);
      if (i0 >= i1) continue;
      for (auto& bt : b_tiles) {
        std::size_t j0 = std::max(cj0, bt.origin().j);
        std::size_t j1 = std::min(cj1, bt.origin().j + bt.shape().j);
        std::size_t k0 = std::max(at.origin().j, bt.origin().i);
        std::size_t k1 = std::min(at.origin().j + at.shape().j,
                                  bt.origin().i + bt.shape().i);
        if (j0 >= j1 || k0 >= k1) continue;
        for (std::size_t i = i0; i < i1; ++i)
          for (std::size_t k = k0; k < k1; ++k) {
            T av = at(i - at.origin().i, k - at.origin().j);
            for (std::size_t j = j0; j < j1; ++j)
              ct(i - ci0, j - cj0) +=
                  av * bt(k - bt.origin().i, j - bt.origin().j);
          }
      }
    }
  }
}

}  // namespace drtpu
