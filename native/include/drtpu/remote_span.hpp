// remote_span: a contiguous slice of a distributed container's logical
// index space owned by one mesh rank — the native analog of the reference's
// lib::remote_subrange (details/remote_subrange.hpp:13-37) and
// shp::device_span (shp/device_span.hpp:43-84), redesigned as a descriptor:
// (rank, global origin, host-visible span).  Rank-preserving first/last/
// subspan mirror device_span's slicing surface.
#pragma once

#include <cstddef>
#include <span>

#include "vocabulary.hpp"

namespace drtpu {

template <class T>
class remote_span {
 public:
  using element_type = T;
  using value_type = std::remove_cv_t<T>;
  using iterator = T*;

  constexpr remote_span() = default;
  constexpr remote_span(std::size_t rank, std::size_t origin,
                        std::span<T> data)
      : rank_(rank), origin_(origin), data_(data) {}

  constexpr std::size_t dr_rank() const { return rank_; }
  constexpr std::span<T> dr_local() const { return data_; }

  constexpr std::size_t origin() const { return origin_; }
  constexpr std::size_t size() const { return data_.size(); }
  constexpr bool empty() const { return data_.empty(); }

  constexpr T* begin() const { return data_.data(); }
  constexpr T* end() const { return data_.data() + data_.size(); }
  constexpr T& operator[](std::size_t i) const { return data_[i]; }

  constexpr remote_span first(std::size_t n) const {
    return {rank_, origin_, data_.first(n)};
  }
  constexpr remote_span last(std::size_t n) const {
    return {rank_, origin_ + size() - n, data_.last(n)};
  }
  constexpr remote_span subspan(std::size_t off, std::size_t n) const {
    return {rank_, origin_ + off, data_.subspan(off, n)};
  }

 private:
  std::size_t rank_ = 0;
  std::size_t origin_ = 0;
  std::span<T> data_{};
};

static_assert(remote_range<remote_span<int>>);
static_assert(remote_contiguous_range<remote_span<int>>);

}  // namespace drtpu
