// Segment-preserving views over distributed ranges: take / drop / subrange /
// transform / zip / enumerate / ranked, with pipeable adaptors.
//
// Native equivalent of the reference's view stack: the ADL segment hooks for
// std views (details/segments_tools.hpp:149-223), the segment-preserving
// lazy transform (views/transform.hpp:9-77), the rank-aware zip with
// intersection segmentation (shp/zip_view.hpp:149-206) and the
// empty-on-misalignment signal (segments_tools.hpp:117-121), and
// views::enumerate / ranked_view (shp/views/enumerate.hpp:27-52,
// views/views.hpp:7-11).  Re-designed for value-descriptor segments: a view
// recomputes its segment list as plain data (no recursive wrapper stack),
// which is also what the TPU path consumes when lowering a view pipeline
// into one fused XLA program.
#pragma once

#include <cstddef>
#include <optional>
#include <tuple>
#include <utility>
#include <vector>

#include "iterator_adaptor.hpp"
#include "segment_tools.hpp"
#include "vocabulary.hpp"

namespace drtpu {

// Marker base: types deriving from view_base are cheap to copy and are
// stored by value inside other views.
struct view_base {};

template <class T>
concept dr_view = std::derived_from<std::remove_cvref_t<T>, view_base>;

namespace detail {

template <class R>
auto segment_vector(R&& r) {
  auto segs = drtpu::segments(r);
  using Seg = std::ranges::range_value_t<decltype(segs)>;
  std::vector<Seg> out;
  for (auto&& s : segs) out.push_back(s);
  return out;
}

}  // namespace detail

// --------------------------------------------------------------------------
// ref_view + all(): lvalue containers are held by pointer, views by value
// --------------------------------------------------------------------------

template <distributed_range R>
class ref_view : public view_base {
 public:
  explicit ref_view(R& r) : r_(&r) {}

  auto begin() const { return std::ranges::begin(*r_); }
  auto end() const { return std::ranges::end(*r_); }
  std::size_t size() const { return std::ranges::size(*r_); }
  auto dr_segments() const { return detail::segment_vector(*r_); }
  R& base() const { return *r_; }

 private:
  R* r_;
};

template <class R>
auto all(R&& r) {
  if constexpr (dr_view<R>)
    return std::forward<R>(r);
  else {
    static_assert(std::is_lvalue_reference_v<R>,
                  "containers must be piped as lvalues");
    return ref_view<std::remove_reference_t<R>>(r);
  }
}

template <class R>
using all_t = decltype(drtpu::all(std::declval<R>()));

// --------------------------------------------------------------------------
// take / drop / subrange
// --------------------------------------------------------------------------

template <dr_view V>
class take_view : public view_base {
 public:
  take_view(V base, std::size_t n) : base_(std::move(base)), n_(n) {}

  std::size_t size() const { return std::min(n_, base_.size()); }
  auto begin() const { return base_.begin(); }
  auto end() const { return base_.begin() + size(); }
  auto dr_segments() const {
    return take_segments(base_.dr_segments(), size());
  }

 private:
  V base_;
  std::size_t n_;
};

template <dr_view V>
class drop_view : public view_base {
 public:
  drop_view(V base, std::size_t n) : base_(std::move(base)), n_(n) {}

  std::size_t size() const {
    return base_.size() - std::min(n_, base_.size());
  }
  auto begin() const { return base_.begin() + std::min(n_, base_.size()); }
  auto end() const { return base_.begin() + base_.size(); }
  auto dr_segments() const {
    return drop_segments(base_.dr_segments(), std::min(n_, base_.size()));
  }

 private:
  V base_;
  std::size_t n_;
};

// --------------------------------------------------------------------------
// transform: lazy op over elements; segments stay distributed
// --------------------------------------------------------------------------

template <class It, class F>
struct transform_accessor {
  using value_type =
      std::remove_cvref_t<std::invoke_result_t<const F&, decltype(*It{})>>;
  using difference_type = std::ptrdiff_t;

  It it{};
  const F* f = nullptr;

  decltype(auto) dereference() const { return (*f)(*it); }
  void operator+=(difference_type n) { it += n; }
  bool operator==(const transform_accessor& o) const { return it == o.it; }
  auto operator<=>(const transform_accessor& o) const { return it <=> o.it; }
  difference_type distance_to(const transform_accessor& o) const {
    return o.it - it;
  }
};

// A transformed segment: still a remote range (rank-preserving), iterable
// on the host through local(); op is stored by value so the segment owns
// everything it needs.
template <class Seg, class F>
class transformed_segment {
 public:
  transformed_segment(Seg s, F f) : s_(std::move(s)), f_(std::move(f)) {}

  std::size_t dr_rank() const { return drtpu::rank(s_); }
  // already host-iterable: iteration applies f over the segment's local data
  const transformed_segment& dr_local() const { return *this; }
  std::size_t size() const { return s_.size(); }
  bool empty() const { return s_.empty(); }

  transformed_segment subspan(std::size_t off, std::size_t n) const {
    return {s_.subspan(off, n), f_};
  }
  transformed_segment first(std::size_t n) const { return subspan(0, n); }
  transformed_segment last(std::size_t n) const {
    return subspan(size() - n, n);
  }

  auto begin() const {
    return iterator_adaptor<
        transform_accessor<decltype(s_.begin()), F>>(
        transform_accessor<decltype(s_.begin()), F>{s_.begin(), &f_});
  }
  auto end() const { return begin() + size(); }
  decltype(auto) operator[](std::size_t i) const { return f_(s_[i]); }

  const Seg& base() const { return s_; }

 private:
  Seg s_;
  F f_;
};

// local() of a transformed segment is itself (already host-iterable); give
// remote_span a shim ctor shape used above via deduction-free path:
template <class Seg, class F>
transformed_segment(Seg, F) -> transformed_segment<Seg, F>;

template <dr_view V, class F>
class transform_view : public view_base {
 public:
  transform_view(V base, F f) : base_(std::move(base)), f_(std::move(f)) {}

  std::size_t size() const { return base_.size(); }
  auto begin() const {
    return iterator_adaptor<
        transform_accessor<decltype(base_.begin()), F>>(
        transform_accessor<decltype(base_.begin()), F>{base_.begin(), &f_});
  }
  auto end() const { return begin() + size(); }

  auto dr_segments() const {
    auto segs = base_.dr_segments();
    using Seg = typename decltype(segs)::value_type;
    std::vector<transformed_segment<Seg, F>> out;
    out.reserve(segs.size());
    for (auto& s : segs) out.emplace_back(s, f_);
    return out;
  }

 private:
  V base_;
  F f_;
};

// --------------------------------------------------------------------------
// zip: intersection segmentation; rank mismatch => empty segments (the
// misalignment signal algorithms test via aligned())
// --------------------------------------------------------------------------

template <class... Its>
struct zip_accessor {
  using value_type = std::tuple<std::remove_cvref_t<decltype(*Its{})>...>;
  using difference_type = std::ptrdiff_t;

  std::tuple<Its...> its{};

  auto dereference() const {
    return std::apply(
        [](const auto&... it) {
          return std::tuple<decltype(*it)...>(*it...);
        },
        its);
  }
  void operator+=(difference_type n) {
    std::apply([n](auto&... it) { ((it += n), ...); }, its);
  }
  bool operator==(const zip_accessor& o) const {
    return std::get<0>(its) == std::get<0>(o.its);
  }
  auto operator<=>(const zip_accessor& o) const {
    return std::get<0>(its) <=> std::get<0>(o.its);
  }
  difference_type distance_to(const zip_accessor& o) const {
    return std::get<0>(o.its) - std::get<0>(its);
  }
};

template <class... Segs>
class zip_segment {
 public:
  explicit zip_segment(Segs... segs) : segs_(std::move(segs)...) {}

  std::size_t dr_rank() const { return drtpu::rank(std::get<0>(segs_)); }
  // already host-iterable: iteration zips the constituents' local data
  const zip_segment& dr_local() const { return *this; }
  std::size_t size() const { return std::get<0>(segs_).size(); }
  bool empty() const { return size() == 0; }

  zip_segment subspan(std::size_t off, std::size_t n) const {
    return std::apply(
        [&](const auto&... s) { return zip_segment(s.subspan(off, n)...); },
        segs_);
  }
  zip_segment first(std::size_t n) const { return subspan(0, n); }
  zip_segment last(std::size_t n) const { return subspan(size() - n, n); }

  auto begin() const {
    return std::apply(
        [](const auto&... s) {
          using Acc = zip_accessor<decltype(s.begin())...>;
          return iterator_adaptor<Acc>(Acc{{s.begin()...}});
        },
        segs_);
  }
  auto end() const { return begin() + size(); }
  auto operator[](std::size_t i) const { return *(begin() + i); }

  const auto& bases() const { return segs_; }

 private:
  std::tuple<Segs...> segs_;
};

template <dr_view... Vs>
class zip_view : public view_base {
 public:
  explicit zip_view(Vs... bases) : bases_(std::move(bases)...) {}

  std::size_t size() const {
    return std::apply(
        [](const auto&... b) { return std::min({b.size()...}); }, bases_);
  }
  auto begin() const {
    return std::apply(
        [](const auto&... b) {
          using Acc = zip_accessor<decltype(b.begin())...>;
          return iterator_adaptor<Acc>(Acc{{b.begin()...}});
        },
        bases_);
  }
  auto end() const { return begin() + size(); }

  // Intersection segmentation (shp/zip_view.hpp:149-167 idea): split all
  // constituent segment lists at every boundary; a rank mismatch on any
  // piece yields the empty-signal.
  auto dr_segments() const {
    auto lists = std::apply(
        [](const auto&... b) { return std::make_tuple(b.dr_segments()...); },
        bases_);
    return zip_lists(lists, size(),
                     std::make_index_sequence<sizeof...(Vs)>{});
  }

 private:
  template <class Lists, std::size_t... I>
  static auto zip_lists(const Lists& lists, std::size_t total,
                        std::index_sequence<I...>) {
    using Z = zip_segment<typename std::tuple_element_t<
        I, Lists>::value_type...>;
    std::vector<Z> out;
    // a constituent that is itself misaligned reports an empty list while
    // still having elements — propagate the empty-signal, don't index it
    if (total > 0 && (std::get<I>(lists).empty() || ...))
      return std::vector<Z>{};
    std::array<std::size_t, sizeof...(I)> seg{}, off{};
    std::size_t done = 0;
    while (done < total) {
      // remaining length of the current segment of each constituent
      std::size_t cut = std::min(
          {total - done,
           (std::get<I>(lists)[seg[I]].size() - off[I])...});
      std::array<std::size_t, sizeof...(I)> ranks{
          drtpu::rank(std::get<I>(lists)[seg[I]])...};
      for (std::size_t r : ranks)
        if (r != ranks[0]) return std::vector<Z>{};  // misaligned signal
      out.push_back(
          Z(std::get<I>(lists)[seg[I]].subspan(off[I], cut)...));
      done += cut;
      ((off[I] += cut, off[I] == std::get<I>(lists)[seg[I]].size()
                           ? (void)(++seg[I], off[I] = 0)
                           : (void)0),
       ...);
    }
    return out;
  }

  std::tuple<Vs...> bases_;
};

// --------------------------------------------------------------------------
// enumerate / ranked
// --------------------------------------------------------------------------

template <class It>
struct enum_accessor {
  using value_type =
      std::pair<std::size_t, std::remove_cvref_t<decltype(*It{})>>;
  using difference_type = std::ptrdiff_t;

  It it{};
  std::size_t gid = 0;

  auto dereference() const {
    return std::pair<std::size_t, decltype(*it)>(gid, *it);
  }
  void operator+=(difference_type n) { it += n; gid += n; }
  bool operator==(const enum_accessor& o) const { return it == o.it; }
  auto operator<=>(const enum_accessor& o) const { return it <=> o.it; }
  difference_type distance_to(const enum_accessor& o) const {
    return o.it - it;
  }
};

template <class Seg>
class enumerated_segment {
 public:
  enumerated_segment(Seg s, std::size_t origin)
      : s_(std::move(s)), origin_(origin) {}

  std::size_t dr_rank() const { return drtpu::rank(s_); }
  // already host-iterable: iteration pairs global indices with local data
  const enumerated_segment& dr_local() const { return *this; }
  std::size_t size() const { return s_.size(); }
  bool empty() const { return s_.empty(); }
  std::size_t origin() const { return origin_; }

  enumerated_segment subspan(std::size_t off, std::size_t n) const {
    return {s_.subspan(off, n), origin_ + off};
  }
  enumerated_segment first(std::size_t n) const { return subspan(0, n); }
  enumerated_segment last(std::size_t n) const {
    return subspan(size() - n, n);
  }

  auto begin() const {
    using Acc = enum_accessor<decltype(s_.begin())>;
    return iterator_adaptor<Acc>(Acc{s_.begin(), origin_});
  }
  auto end() const { return begin() + size(); }

 private:
  Seg s_;
  std::size_t origin_;
};

template <dr_view V>
class enumerate_view : public view_base {
 public:
  explicit enumerate_view(V base) : base_(std::move(base)) {}

  std::size_t size() const { return base_.size(); }

  auto dr_segments() const {
    auto segs = base_.dr_segments();
    using Seg = typename decltype(segs)::value_type;
    std::vector<enumerated_segment<Seg>> out;
    std::size_t origin = 0;
    for (auto& s : segs) {
      out.emplace_back(s, origin);
      origin += s.size();
    }
    return out;
  }

  auto begin() const {
    using Acc = enum_accessor<decltype(base_.begin())>;
    return iterator_adaptor<Acc>(Acc{base_.begin(), 0});
  }
  auto end() const { return begin() + size(); }

 private:
  V base_;
};

// segment_range: iota-like range of per-segment position ids — the
// reference's shp::id<1> + shp::segment_range (shp/range.hpp:12-130).
// Each element carries (segment, local index, global index) and converts
// to the global index.
class seg_id {
 public:
  seg_id() = default;
  seg_id(std::size_t segment, std::size_t local, std::size_t global)
      : segment_(segment), local_(local), global_(global) {}

  operator std::size_t() const { return global_; }
  std::size_t segment() const { return segment_; }
  std::size_t local_id() const { return local_; }
  std::size_t global_id() const { return global_; }

 private:
  std::size_t segment_ = 0;
  std::size_t local_ = 0;
  std::size_t global_ = 0;
};

struct segment_range_accessor {
  using value_type = seg_id;
  using difference_type = std::ptrdiff_t;

  std::size_t segment = 0;
  std::size_t idx = 0;
  std::size_t offset = 0;

  seg_id dereference() const { return {segment, idx, offset + idx}; }
  void operator+=(difference_type n) { idx += n; }
  bool operator==(const segment_range_accessor& o) const {
    return segment == o.segment && idx == o.idx;
  }
  auto operator<=>(const segment_range_accessor& o) const {
    return idx <=> o.idx;
  }
  difference_type distance_to(const segment_range_accessor& o) const {
    return difference_type(o.idx) - difference_type(idx);
  }
};

class segment_range {
 public:
  using value_type = seg_id;
  using iterator = iterator_adaptor<segment_range_accessor>;

  segment_range(std::size_t segment_id, std::size_t segment_size,
                std::size_t global_offset)
      : segment_id_(segment_id), size_(segment_size),
        offset_(global_offset) {}

  iterator begin() const {
    return iterator(segment_range_accessor{segment_id_, 0, offset_});
  }
  iterator end() const { return begin() + size_; }
  std::size_t size() const { return size_; }
  seg_id operator[](std::size_t i) const { return *(begin() + i); }
  // the reference returns rank 0 unconditionally (shp/range.hpp:124)
  std::size_t dr_rank() const { return 0; }

 private:
  std::size_t segment_id_;
  std::size_t size_;
  std::size_t offset_;
};

// ranked_view: debug view of (owning rank, value) pairs (views/views.hpp:7-11)
template <dr_view V>
class ranked_view : public view_base {
 public:
  explicit ranked_view(V base) : base_(std::move(base)) {}

  std::size_t size() const { return base_.size(); }

  auto pairs() const {  // materialized (rank, value) list
    using T = std::remove_cvref_t<decltype(*base_.begin())>;
    std::vector<std::pair<std::size_t, T>> out;
    out.reserve(size());
    for (auto& s : base_.dr_segments())
      for (auto&& v : drtpu::local(s)) out.emplace_back(drtpu::rank(s), v);
    return out;
  }

 private:
  V base_;
};

// --------------------------------------------------------------------------
// pipeable adaptors: r | views::take(3) | views::transform(f)
// --------------------------------------------------------------------------

namespace views {

namespace detail {

template <class Fn>
struct closure {
  Fn fn;
  template <class R>
  friend auto operator|(R&& r, const closure& c) {
    return c.fn(std::forward<R>(r));
  }
  template <class R>
  auto operator()(R&& r) const {
    return fn(std::forward<R>(r));
  }
};
template <class Fn>
closure(Fn) -> closure<Fn>;

}  // namespace detail

inline auto take(std::size_t n) {
  return detail::closure{[n](auto&& r) {
    return take_view(drtpu::all(std::forward<decltype(r)>(r)), n);
  }};
}
template <class R>
auto take(R&& r, std::size_t n) {
  return take_view(drtpu::all(std::forward<R>(r)), n);
}

inline auto drop(std::size_t n) {
  return detail::closure{[n](auto&& r) {
    return drop_view(drtpu::all(std::forward<decltype(r)>(r)), n);
  }};
}
template <class R>
auto drop(R&& r, std::size_t n) {
  return drop_view(drtpu::all(std::forward<R>(r)), n);
}

inline auto subrange(std::size_t first, std::size_t last) {
  return detail::closure{[first, last](auto&& r) {
    return take_view(
        drop_view(drtpu::all(std::forward<decltype(r)>(r)), first),
        last - first);
  }};
}
template <class R>
auto subrange(R&& r, std::size_t first, std::size_t last) {
  return take_view(drop_view(drtpu::all(std::forward<R>(r)), first),
                   last - first);
}

// slice = subrange (shp/views/standard_views.hpp:19-44 naming)
inline auto slice(std::size_t first, std::size_t last) {
  return subrange(first, last);
}

template <class F>
auto transform(F f) {
  return detail::closure{[f = std::move(f)](auto&& r) {
    return transform_view(drtpu::all(std::forward<decltype(r)>(r)), f);
  }};
}
template <distributed_range R, class F>
auto transform(R&& r, F f) {
  return transform_view(drtpu::all(std::forward<R>(r)), std::move(f));
}

template <class... Rs>
auto zip(Rs&&... rs) {
  return zip_view(drtpu::all(std::forward<Rs>(rs))...);
}

inline auto enumerate() {
  return detail::closure{[](auto&& r) {
    return enumerate_view(drtpu::all(std::forward<decltype(r)>(r)));
  }};
}
template <distributed_range R>
auto enumerate(R&& r) {
  return enumerate_view(drtpu::all(std::forward<R>(r)));
}

template <distributed_range R>
auto ranked(R&& r) {
  return ranked_view(drtpu::all(std::forward<R>(r)));
}

}  // namespace views

}  // namespace drtpu
