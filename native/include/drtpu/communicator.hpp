// Native communicator + logger for the host executor.
//
// The reference wraps an MPI communicator (`lib::communicator`,
// include/dr/details/communicator.hpp:7-95: rank topology, barrier,
// bcast/scatter(v)/gather(v), nonblocking p2p with the halo tag enum) and
// a global per-rank file logger (`lib::drlog`, details/logger.hpp:7-49).
// The host executor models P ranks inside one process, so the same
// surface operates on per-rank value slots: collectives are memcpys, the
// barrier is a no-op, and the ring shifts are the p2p plane the halo
// engine uses (tag {halo_forward, halo_reverse} equivalents).  The TPU
// executor's counterpart is dr_tpu/parallel/collectives.py (ppermute /
// psum / all_gather over the mesh axis).
#pragma once

#include <algorithm>
#include <cstdarg>
#include <cstddef>
#include <cstdio>
#include <stdexcept>
#include <string>
#include <vector>

namespace drtpu {

// ---------------------------------------------------------------------------
// communicator
// ---------------------------------------------------------------------------

class communicator {
 public:
  explicit communicator(std::size_t nprocs) : nprocs_(nprocs) {
    if (!nprocs) throw std::invalid_argument("communicator: nprocs == 0");
  }

  std::size_t size() const { return nprocs_; }
  std::size_t first() const { return 0; }
  std::size_t last() const { return nprocs_ - 1; }
  std::size_t prev(std::size_t rank) const {
    return (rank + nprocs_ - 1) % nprocs_;
  }
  std::size_t next(std::size_t rank) const { return (rank + 1) % nprocs_; }

  // All P ranks live in this process: the barrier is trivially satisfied.
  void barrier() const {}

  // slots[r] is rank r's value; bcast copies root's slot everywhere
  // (communicator.hpp:32).
  template <class T>
  void bcast(std::vector<T>& slots, std::size_t root) const {
    check_slots(slots.size());
    if (root >= nprocs_)
      throw std::invalid_argument("bcast: root out of range");
    for (std::size_t r = 0; r < nprocs_; ++r)
      if (r != root) slots[r] = slots[root];
  }

  // scatter(v): root's vector of P values lands one per rank
  // (communicator.hpp:36-45).
  template <class T>
  void scatter(const std::vector<T>& values, std::vector<T>& slots) const {
    check_slots(values.size());
    check_slots(slots.size());
    for (std::size_t r = 0; r < nprocs_; ++r) slots[r] = values[r];
  }

  // gather(v): every rank's value lands in root's vector, rank order
  // (communicator.hpp:47-62).  Shared memory: every caller sees it.
  template <class T>
  void gather(const std::vector<T>& slots, std::vector<T>& out) const {
    check_slots(slots.size());
    out = slots;
  }

  // Ring shifts — the halo p2p plane (tag halo_forward / halo_reverse).
  // Non-periodic edges keep their old value, matching the span_halo rule.
  template <class T>
  void shift_forward(std::vector<T>& slots, bool periodic = false) const {
    check_slots(slots.size());
    if (nprocs_ == 1) return;
    T edge = slots[nprocs_ - 1];
    for (std::size_t r = nprocs_ - 1; r > 0; --r)
      slots[r] = slots[r - 1];
    if (periodic) slots[0] = edge;
  }

  template <class T>
  void shift_backward(std::vector<T>& slots, bool periodic = false) const {
    check_slots(slots.size());
    if (nprocs_ == 1) return;
    T edge = slots[0];
    for (std::size_t r = 0; r + 1 < nprocs_; ++r) slots[r] = slots[r + 1];
    if (periodic) slots[nprocs_ - 1] = edge;
  }

  // alltoall: slots[r][c] -> out[c][r] (the transpose of the mailbox
  // grid).  Alias-safe: builds into a temporary so alltoall(g, g) works.
  template <class T>
  void alltoall(const std::vector<std::vector<T>>& slots,
                std::vector<std::vector<T>>& out) const {
    check_slots(slots.size());
    std::vector<std::vector<T>> t(nprocs_, std::vector<T>(nprocs_));
    for (std::size_t r = 0; r < nprocs_; ++r) {
      if (slots[r].size() != nprocs_)
        throw std::invalid_argument("alltoall: ragged slot row");
      for (std::size_t c = 0; c < nprocs_; ++c) t[c][r] = slots[r][c];
    }
    out = std::move(t);
  }

 private:
  void check_slots(std::size_t got) const {
    if (got != nprocs_)
      throw std::invalid_argument("communicator: slot count != nprocs");
  }

  std::size_t nprocs_;
};

// ---------------------------------------------------------------------------
// rma_window (lib::rma_window, details/communicator.hpp:97-149)
// ---------------------------------------------------------------------------

// One-sided window: each rank registers its local block; get/put address
// (rank, offset) pairs.  The reference backs this with MPI_Rget/MPI_Put +
// fence/flush; in the shared-memory host executor the window is a table
// of spans and the sync calls are ordering no-ops.  The TPU executor's
// counterpart is the batched collectives.rma_window (the explicit-batch
// redesign of per-element RMA, SURVEY §2.5).
template <class T>
class rma_window {
 public:
  rma_window() = default;
  explicit rma_window(std::size_t nprocs)
      : data_(nprocs, nullptr), count_(nprocs, 0) {}

  void create(std::size_t rank, T* block, std::size_t count) {
    check_rank(rank);
    data_[rank] = block;
    count_[rank] = count;
  }

  void free_window() {
    std::fill(data_.begin(), data_.end(), nullptr);
    std::fill(count_.begin(), count_.end(), std::size_t{0});
  }

  T get(std::size_t rank, std::size_t idx) const {
    check_elem(rank, idx);
    return data_[rank][idx];
  }

  void put(std::size_t rank, std::size_t idx, const T& value) {
    check_elem(rank, idx);
    data_[rank][idx] = value;
  }

  // Single process: all puts are visible at return; these order only.
  void fence() const {}
  void flush(std::size_t rank) const { check_rank(rank); }

  std::size_t size(std::size_t rank) const {
    check_rank(rank);
    return count_[rank];
  }

 private:
  void check_rank(std::size_t rank) const {
    if (rank >= data_.size())
      throw std::invalid_argument("rma_window: rank out of range");
  }
  void check_elem(std::size_t rank, std::size_t idx) const {
    check_rank(rank);
    if (!data_[rank])
      throw std::logic_error("rma_window: rank has no attached block");
    if (idx >= count_[rank])
      throw std::out_of_range("rma_window: index outside window");
  }

  std::vector<T*> data_;
  std::vector<std::size_t> count_;
};

// ---------------------------------------------------------------------------
// logger (lib::drlog, details/logger.hpp:7-49)
// ---------------------------------------------------------------------------

// Global logger with an optional file sink; a no-op until set_file() is
// called (the reference compiles to nothing without DR_FORMAT — here the
// gate is runtime instead of compile-time).  printf-style because the
// toolchain (g++ 12) lacks <format>.
class logger {
 public:
  ~logger() { close(); }

  void set_file(const std::string& path) {
    close();
    sink_ = std::fopen(path.c_str(), "w");
    if (!sink_) throw std::runtime_error("drlog: cannot open " + path);
  }

  void close() {
    if (sink_) {
      std::fclose(sink_);
      sink_ = nullptr;
    }
  }

  bool active() const { return sink_ != nullptr; }

#if defined(__GNUC__)
  __attribute__((format(printf, 4, 5)))
#endif
  void debug(const char* file, int line, const char* fmt, ...) {
    if (!sink_) return;
    std::fprintf(sink_, "%s:%d: ", file, line);
    std::va_list ap;
    va_start(ap, fmt);
    std::vfprintf(sink_, fmt, ap);
    va_end(ap);
    std::fputc('\n', sink_);
    std::fflush(sink_);
  }

 private:
  std::FILE* sink_ = nullptr;
};

inline logger drlog;  // the global instance (lib::drlog analog)

// Call-site capture like the reference's source_location prefix
// (logger.hpp:13-28).
#define DRTPU_LOG(...) ::drtpu::drlog.debug(__FILE__, __LINE__, __VA_ARGS__)

}  // namespace drtpu
