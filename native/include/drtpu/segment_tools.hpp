// Segment recomputation tools: take/drop windows over segment lists, the
// zip alignment rule, and local_segments — native equivalents of the
// reference's segments_tools.hpp:38-122 and mhp/alignment.hpp:13-28.
//
// Segments here are value descriptors (remote_span or anything with
// size()/subspan()/dr_rank()), so recomputation is plain slicing — no
// recursive view wrappers needed.
#pragma once

#include <cstddef>
#include <vector>

#include "remote_span.hpp"
#include "vocabulary.hpp"

namespace drtpu {

template <class Seg>
concept sliceable_segment = requires(const Seg& s, std::size_t k) {
  { s.size() } -> std::convertible_to<std::size_t>;
  { s.subspan(k, k) } -> std::convertible_to<Seg>;
  { drtpu::rank(s) } -> std::convertible_to<std::size_t>;
};

// First n elements of a segment list, trimming the cut segment.
template <sliceable_segment Seg>
std::vector<Seg> take_segments(const std::vector<Seg>& segs, std::size_t n) {
  std::vector<Seg> out;
  std::size_t remaining = n;
  for (const auto& s : segs) {
    if (remaining == 0) break;
    std::size_t k = s.size() < remaining ? s.size() : remaining;
    out.push_back(s.subspan(0, k));
    remaining -= k;
  }
  return out;
}

// Drop the first n elements of a segment list.
template <sliceable_segment Seg>
std::vector<Seg> drop_segments(const std::vector<Seg>& segs, std::size_t n) {
  std::vector<Seg> out;
  std::size_t todrop = n;
  for (const auto& s : segs) {
    if (todrop >= s.size()) {
      todrop -= s.size();
      continue;
    }
    out.push_back(s.subspan(todrop, s.size() - todrop));
    todrop = 0;
  }
  return out;
}

template <sliceable_segment Seg>
std::vector<Seg> subrange_segments(const std::vector<Seg>& segs,
                                   std::size_t first, std::size_t last) {
  return take_segments(drop_segments(segs, first), last - first);
}

// Pairwise (rank, size) equality of segment lists — the aligned() rule.
// Misalignment is the empty-zip signal (segments_tools.hpp:117-121).
template <sliceable_segment Seg>
bool aligned_segments(const std::vector<std::vector<Seg>>& lists) {
  if (lists.empty()) return true;
  const auto& first = lists.front();
  for (std::size_t li = 1; li < lists.size(); ++li) {
    const auto& other = lists[li];
    if (other.size() != first.size()) return false;
    for (std::size_t i = 0; i < first.size(); ++i) {
      if (drtpu::rank(first[i]) != drtpu::rank(other[i]) ||
          first[i].size() != other[i].size())
        return false;
    }
  }
  return true;
}

template <distributed_range R1, distributed_range... Rs>
bool aligned(R1&& r1, Rs&&... rs) {
  using Seg = std::ranges::range_value_t<decltype(drtpu::segments(r1))>;
  std::vector<std::vector<Seg>> lists;
  auto collect = [&](auto&& r) {
    std::vector<Seg> v;
    for (auto&& s : drtpu::segments(r)) v.push_back(s);
    lists.push_back(std::move(v));
  };
  collect(r1);
  (collect(rs), ...);
  for (const auto& l : lists)
    if (l.empty()) return false;
  return aligned_segments(lists);
}

// Device-local pieces of every segment (mhp/views.hpp:9-21): on the
// single-controller runtime every shard is addressable.
template <distributed_range R>
auto local_segments(R&& r) {
  auto segs = drtpu::segments(r);
  using Local = decltype(drtpu::local(*segs.begin()));
  std::vector<Local> out;
  for (auto&& s : segs) out.push_back(drtpu::local(s));
  return out;
}

}  // namespace drtpu
