// thp_bridge: C++ driver for the TPU execution backend.
//
// The reference's backends bind C++ to MPI (mhp) or SYCL (shp); the TPU
// equivalent binds C++ to the embedded JAX/XLA runtime (the BASELINE.json
// north-star "thin bridge": a C++ thp:: surface whose containers live as
// shards of jax.Arrays on the device mesh).  The bridge uses the CPython
// C API directly (no pybind11 in this image): one interpreter, GIL held by
// the calling thread, jax programs dispatched asynchronously by the
// runtime underneath.
//
// User ops are expressed in a small arithmetic DSL (thp::expr over
// placeholders thp::x0..x3) serialized to a canonical string and compiled
// ONCE on the Python side into a jax-traceable callable
// (dr_tpu/utils/expr.py) — the reference's C++-lambda surface
// (cpu_algorithms.hpp:63-74, for_each.hpp:16-92) re-imagined for a traced
// backend (SURVEY.md §7 hard-part 2, option (a)).  Equal expression
// strings share one callable, so the algorithm layer's identity-keyed
// program caches reuse compiled XLA programs across bridge calls.
//
// Surface (mirrors the Python dr_tpu API; reference parity targets:
// include/dr/shp/shp.hpp:8-26, include/dr/mhp.hpp:41-59):
//   thp::session s(ncpu_devices /*0 = real TPU*/);
//   thp::vector v = s.make_vector(n, halo_prev, halo_next, periodic);
//   thp::vector u = s.make_vector_blocks({10, 0, 24, 23});  // teams
//   v.iota(0); v.fill(1.0);
//   double r = v.reduce();  double d = s.dot(a, b);
//   s.transform(a, out, thp::x0 * 2.0 + 1.0);          // lazy op DSL
//   s.transform2(a, b, out, thp::x0 * thp::x1);        // zipped binary
//   s.for_each(v, thp::sqrt(thp::abs(thp::x0)));
//   s.inclusive_scan(in, out);  s.exclusive_scan(in, out, init);
//   thp::sparse_matrix A = s.make_sparse_coo(m, n, rows, cols, vals);
//   s.gemv(c, A, b);                                    // c += A·b
//   thp::dense_matrix M = s.make_dense(m, n, host_data);
//   thp::mdarray T = s.make_mdarray({a, b, c}, host_data);  // N-D
//   s.transpose(out_md, in_md, {2, 0, 1});              // all-to-all T
//   thp::mdspan W = s.submdspan(T, {{2, 9}, {0, b}, {3, 8}});
//   s.stencil_iterate(a, b, {w...}, steps);
//   std::vector<double> host = v.to_host();  // buffer-protocol copy
#pragma once

#include <cstddef>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

namespace thp {

class session;

// Element dtype of a device container.  The reference templates its
// containers over T (mhp/containers/distributed_vector.hpp:176); the
// bridge keys the DEVICE dtype (what occupies HBM and feeds the
// MXU/VPU) while the host interchange stays double — to_host()
// converts on the way out, scalar arguments convert on the way in.
// f32 is the default (TPU-native; also what pre-dtype bridge versions
// allocated); f64 needs an x64-enabled CPU backend — make_vector
// fails loudly when f64 is requested with JAX x64 disabled, instead
// of silently allocating an f32 buffer under an f64 label.
enum class dtype { f32, f64, i32 };

// Multi-process SPMD membership (the MHP dimension): every process
// constructs a session with the SAME coordinator and runs the SAME
// program in the same order — the discipline the reference gets from
// MPI (mhp/global.hpp:24-28, mpiexec -n {1..4} test sweeps).  Backed
// by jax.distributed over DCN; the global mesh spans
// num_processes * ncpu_devices devices.
struct distributed {
  std::string coordinator;   // "host:port" (process 0 binds it)
  int num_processes = 1;
  int process_id = 0;
  int ncpu_devices = 1;      // per-process virtual CPU devices (tests)
};

// ---------------------------------------------------------------------
// expression DSL: value-semantics nodes serializing to canonical strings
// ---------------------------------------------------------------------
class expr {
 public:
  static expr arg(int i);      // placeholder xi
  static expr lit(double v);   // numeric literal
  const std::string& str() const { return s_; }

  // internal: wraps an already-serialized subexpression (used by the
  // operator overloads; not a user entry point — the grammar is
  // validated Python-side before compilation either way)
  struct raw_t {};
  expr(raw_t, std::string s) : s_(std::move(s)) {}

 private:
  std::string s_;
};

expr operator+(const expr& a, const expr& b);
expr operator-(const expr& a, const expr& b);
expr operator*(const expr& a, const expr& b);
expr operator/(const expr& a, const expr& b);
expr operator-(const expr& a);
expr operator+(const expr& a, double b);
expr operator+(double a, const expr& b);
expr operator-(const expr& a, double b);
expr operator-(double a, const expr& b);
expr operator*(const expr& a, double b);
expr operator*(double a, const expr& b);
expr operator/(const expr& a, double b);
expr operator/(double a, const expr& b);
expr sqrt(const expr& a);
expr exp(const expr& a);
expr log(const expr& a);
expr tanh(const expr& a);
expr abs(const expr& a);
expr min(const expr& a, const expr& b);
expr max(const expr& a, const expr& b);
expr pow(const expr& a, const expr& b);

// ready-made placeholders (x0 = first range/zip component, ...)
extern const expr x0, x1, x2, x3;

// Escape hatch (SURVEY §7 hard-part 2, option b): an op the arithmetic
// DSL cannot express, written as jax-traceable Python source that
// evaluates to a callable of `nargs` placeholders — conditionals,
// comparisons, clips, casts, anything traceable.  `jnp`, `lax`, `np`
// are in scope.  Same trust boundary as session::exec (the C++ caller
// owns the embedded interpreter); compiled once per (source, nargs)
// Python-side so program caches stay warm across calls.
//   thp::custom_op leaky{"lambda x0: jnp.where(x0 > 0, x0, 0.01*x0)", 1};
//   s.for_each(v, leaky);
struct custom_op {
  std::string source;
  int nargs = 1;
};

// ---------------------------------------------------------------------
// containers: move-only handles owning a PyObject* of the dr_tpu object
// ---------------------------------------------------------------------
namespace detail {
class handle {
 public:
  handle() = default;
  ~handle();
  handle(handle&&) noexcept;
  handle& operator=(handle&&) noexcept;
  handle(const handle&) = delete;
  handle& operator=(const handle&) = delete;

 protected:
  friend class ::thp::session;
  handle(session* s, void* obj) : sess_(s), obj_(obj) {}
  session* sess_ = nullptr;
  void* obj_ = nullptr;  // PyObject*
};
}  // namespace detail

class vector : public detail::handle {
 public:
  vector() = default;
  std::size_t size() const { return n_; }
  dtype element_dtype() const { return dt_; }

  void iota(double start);
  void fill(double value);
  double reduce() const;
  void halo_exchange();
  // buffer-protocol host copy: ONE contiguous memcpy, no element
  // boxing; non-f64 device dtypes convert numpy-side on the way out
  std::vector<double> to_host() const;

 private:
  friend class session;
  vector(session* s, void* obj, std::size_t n, dtype dt = dtype::f32)
      : handle(s, obj), n_(n), dt_(dt) {}
  std::size_t n_ = 0;
  dtype dt_ = dtype::f32;
};

class dense_matrix : public detail::handle {
 public:
  dense_matrix() = default;
  std::size_t rows() const { return m_; }
  std::size_t cols() const { return n_; }
  std::vector<double> to_host() const;  // row-major m*n

 private:
  friend class session;
  dense_matrix(session* s, void* obj, std::size_t m, std::size_t n)
      : handle(s, obj), m_(m), n_(n) {}
  std::size_t m_ = 0, n_ = 0;
};

class sparse_matrix : public detail::handle {
 public:
  sparse_matrix() = default;
  std::size_t rows() const { return m_; }
  std::size_t cols() const { return n_; }
  std::size_t nnz() const { return nnz_; }

 private:
  friend class session;
  sparse_matrix(session* s, void* obj, std::size_t m, std::size_t n,
                std::size_t nnz)
      : handle(s, obj), m_(m), n_(n), nnz_(nnz) {}
  std::size_t m_ = 0, n_ = 0, nnz_ = 0;
};

class mdarray : public detail::handle {
 public:
  mdarray() = default;
  // N-D (round 5): the spec'd surface is arbitrary rank
  // (doc/spec/source/containers/distributed_mdarray.rst:12-23); the
  // Python container has been N-D since round 3 — the bridge now
  // reaches all of it.
  const std::vector<std::size_t>& shape() const { return shape_; }
  std::size_t rank() const { return shape_.size(); }
  // 2-D convenience accessors (the historical surface)
  std::size_t rows() const { return shape_.empty() ? 0 : shape_[0]; }
  std::size_t cols() const { return shape_.size() < 2 ? 1 : shape_[1]; }
  std::vector<double> to_host() const;  // row-major, product(shape)

 private:
  friend class session;
  mdarray(session* s, void* obj, std::vector<std::size_t> shape)
      : handle(s, obj), shape_(std::move(shape)) {}
  std::vector<std::size_t> shape_;
};

// Non-owning N-D window over an mdarray (the spec's submdspan;
// Python: distributed_mdspan).  to_host() materializes ONLY the
// window, row-major over the window's shape.
class mdspan : public detail::handle {
 public:
  mdspan() = default;
  const std::vector<std::size_t>& shape() const { return shape_; }
  std::size_t rank() const { return shape_.size(); }
  std::vector<double> to_host() const;

 private:
  friend class session;
  mdspan(session* s, void* obj, std::vector<std::size_t> shape)
      : handle(s, obj), shape_(std::move(shape)) {}
  std::vector<std::size_t> shape_;
};

// ---------------------------------------------------------------------
// session: the embedded runtime + the algorithm surface
// ---------------------------------------------------------------------
class session {
 public:
  // ncpu_devices > 0: force a virtual CPU mesh of that size (testing);
  // ncpu_devices == 0: use the real device platform (TPU under the driver).
  explicit session(int ncpu_devices = 0);
  // multi-process SPMD member: joins the coordinator's global mesh
  // (dr_tpu.init_distributed / jax.distributed underneath).  All
  // processes must make the same calls in the same order.
  explicit session(const distributed& d);
  ~session();
  session(const session&) = delete;
  session& operator=(const session&) = delete;

  std::size_t nprocs() const;

  // containers
  vector make_vector(std::size_t n, std::size_t halo_prev = 0,
                     std::size_t halo_next = 0, bool periodic = false,
                     dtype dt = dtype::f32);
  // uneven block distribution (round 5): shard r owns sizes[r]
  // contiguous elements; zero sizes express "teams" (the Python
  // container's block_distribution surface reached from C++; halo
  // requires the uniform layout, so these take none).  A distinct
  // NAME, not an overload: make_vector({64}) would silently prefer
  // the scalar size_t conversion and drop the distribution intent
  vector make_vector_blocks(const std::vector<std::size_t>& sizes,
                            dtype dt = dtype::f32);
  dense_matrix make_dense(std::size_t m, std::size_t n,
                          const std::vector<double>& row_major = {});
  sparse_matrix make_sparse_coo(std::size_t m, std::size_t n,
                                const std::vector<std::int64_t>& rows,
                                const std::vector<std::int64_t>& cols,
                                const std::vector<double>& values);
  // N-D mdarray over an arbitrary shape (round 5); the (m, n) form
  // below is the historical 2-D convenience.
  mdarray make_mdarray(const std::vector<std::size_t>& shape,
                       const std::vector<double>& row_major = {});
  mdarray make_mdarray(std::size_t m, std::size_t n,
                       const std::vector<double>& row_major = {});
  // half-open [lo, hi) windows, one per dimension (rank must match)
  mdspan submdspan(
      const mdarray& a,
      const std::vector<std::pair<std::size_t, std::size_t>>& box);

  // elementwise / reduction algorithms (op = DSL expression)
  void transform(const vector& in, vector& out, const expr& op);
  void transform2(const vector& a, const vector& b, vector& out,
                  const expr& op);  // zip(a, b) | transform
  void for_each(vector& v, const expr& op);
  double transform_reduce(const vector& v, const expr& op);
  double dot(const vector& a, const vector& b);

  // the same algorithms with the custom-op escape hatch
  void transform(const vector& in, vector& out, const custom_op& op);
  void transform2(const vector& a, const vector& b, vector& out,
                  const custom_op& op);
  void for_each(vector& v, const custom_op& op);
  double transform_reduce(const vector& v, const custom_op& op);

  // prefix scans (add monoid — the reference's inclusive_scan surface)
  void inclusive_scan(const vector& in, vector& out);
  void exclusive_scan(const vector& in, vector& out, double init = 0.0);
  // windowed forms (round 5): scan in[ilo, ihi) into out[olo, ohi) —
  // equal lengths; offsets/distributions may differ (the Python layer
  // realigns window-coordinate results with one masked all_to_all)
  void inclusive_scan(const vector& in, std::size_t ilo, std::size_t ihi,
                      vector& out, std::size_t olo, std::size_t ohi);
  void exclusive_scan(const vector& in, std::size_t ilo, std::size_t ihi,
                      vector& out, std::size_t olo, std::size_t ohi,
                      double init = 0.0);

  // distributed sample sort, in place (beyond-parity surface; one
  // shard_map program: local sort + splitter all_gather + all_to_all
  // bucket exchange + rebalance — algorithms/sort.py); the _by_key
  // form reorders values by keys, STABLY (payload rides the same
  // collectives)
  void sort(vector& v, bool descending = false);
  void sort_by_key(vector& keys, vector& values, bool descending = false);
  vector argsort(const vector& v, bool descending = false);  // int32 perm
  bool is_sorted(const vector& v);
  // subrange-window forms (round 5 — the Python windows reached from
  // C++): half-open [lo, hi); sort_by_key windows may overlap when
  // keys and values share one vector (payload-last blend order), and
  // key/value windows must have equal lengths
  void sort(vector& v, std::size_t lo, std::size_t hi,
            bool descending = false);
  void sort_by_key(vector& keys, std::size_t klo, std::size_t khi,
                   vector& values, std::size_t vlo, std::size_t vhi,
                   bool descending = false);
  bool is_sorted(const vector& v, std::size_t lo, std::size_t hi);

  // matrix algorithms
  void gemv(vector& c, const sparse_matrix& a, const vector& b);
  void gemm(const dense_matrix& a, const dense_matrix& b,
            dense_matrix& out);
  // out = in permuted by axes (empty = reversed, numpy's default);
  // lowers to an XLA all-to-all over the mesh (containers/mdarray.py)
  void transpose(mdarray& out, const mdarray& in,
                 const std::vector<std::size_t>& axes = {});

  // stencil: weights.size() must be halo_prev + halo_next + 1
  void stencil_iterate(vector& a, vector& b,
                       const std::vector<double>& weights, int steps);

  // checkpoint / restore (Python layer utils/checkpoint.py; the
  // reference has no serialization at all — SURVEY §5)
  void save(const std::string& path, const vector& v);
  vector load_vector(const std::string& path);

  // escape hatch: run a statement in the embedded interpreter
  void exec(const std::string& code);

 private:
  friend class vector;
  friend class dense_matrix;
  friend class sparse_matrix;
  friend class mdarray;
  friend class mdspan;
  friend class detail::handle;
  struct impl;
  std::unique_ptr<impl> impl_;
};

}  // namespace thp
