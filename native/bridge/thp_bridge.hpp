// thp_bridge: C++ driver for the TPU execution backend.
//
// The reference's backends bind C++ to MPI (mhp) or SYCL (shp); the TPU
// equivalent binds C++ to the embedded JAX/XLA runtime (the BASELINE.json
// north-star "thin bridge": a C++ thp:: surface whose containers live as
// shards of jax.Arrays on the device mesh).  The bridge uses the CPython
// C API directly (no pybind11 in this image): one interpreter, GIL held by
// the calling thread, jax programs dispatched asynchronously by the
// runtime underneath.
//
// Surface (mirrors the Python dr_tpu API; extend as needed):
//   thp::session s(ncpu_devices /*0 = real TPU*/);
//   thp::vector v = s.vector(n, halo_prev, halo_next, periodic);
//   v.iota(0); v.fill(1.0);
//   double r = v.reduce();  double d = s.dot(a, b);
//   s.stencil_iterate(a, b, {w...}, steps);
//   std::vector<double> host = v.to_host();
#pragma once

#include <cstddef>
#include <memory>
#include <string>
#include <vector>

namespace thp {

class session;

class vector {
 public:
  vector() = default;
  ~vector();
  vector(vector&&) noexcept;
  vector& operator=(vector&&) noexcept;
  vector(const vector&) = delete;
  vector& operator=(const vector&) = delete;

  std::size_t size() const { return n_; }

  void iota(double start);
  void fill(double value);
  double reduce() const;
  void halo_exchange();
  std::vector<double> to_host() const;

 private:
  friend class session;
  vector(session* s, void* obj, std::size_t n)
      : sess_(s), obj_(obj), n_(n) {}
  session* sess_ = nullptr;
  void* obj_ = nullptr;  // PyObject* of the dr_tpu.distributed_vector
  std::size_t n_ = 0;
};

class session {
 public:
  // ncpu_devices > 0: force a virtual CPU mesh of that size (testing);
  // ncpu_devices == 0: use the real device platform (TPU under the driver).
  explicit session(int ncpu_devices = 0);
  ~session();
  session(const session&) = delete;
  session& operator=(const session&) = delete;

  std::size_t nprocs() const;

  vector make_vector(std::size_t n, std::size_t halo_prev = 0,
                     std::size_t halo_next = 0, bool periodic = false);
  double dot(const vector& a, const vector& b);
  // weights.size() must be halo_prev + halo_next + 1
  void stencil_iterate(vector& a, vector& b,
                       const std::vector<double>& weights, int steps);

  // escape hatch: run a statement in the embedded interpreter
  void exec(const std::string& code);

 private:
  friend class vector;
  struct impl;
  std::unique_ptr<impl> impl_;
};

}  // namespace thp
