// thp::expr DSL serializer — its OWN translation unit, deliberately
// free of any Python dependency: the native fuzz harness
// (tests/fuzz_native.cpp) links it stand-alone to property-test the
// serialized grammar, and `make -C native test` must keep building on
// a machine with only a C++20 compiler (no python3-config --embed).
#include "thp_bridge.hpp"

#include <cstdio>
#include <string>

namespace thp {

namespace {
std::string num(double v) {
  char buf[64];
  std::snprintf(buf, sizeof buf, "%.17g", v);
  return buf;
}
expr mk(std::string s) { return expr(expr::raw_t{}, std::move(s)); }
}  // namespace

expr expr::arg(int i) { return mk("x" + std::to_string(i)); }
expr expr::lit(double v) { return mk(num(v)); }

expr operator+(const expr& a, const expr& b) {
  return mk("(" + a.str() + " + " + b.str() + ")");
}
expr operator-(const expr& a, const expr& b) {
  return mk("(" + a.str() + " - " + b.str() + ")");
}
expr operator*(const expr& a, const expr& b) {
  return mk("(" + a.str() + " * " + b.str() + ")");
}
expr operator/(const expr& a, const expr& b) {
  return mk("(" + a.str() + " / " + b.str() + ")");
}
expr operator-(const expr& a) { return mk("(0 - " + a.str() + ")"); }
expr operator+(const expr& a, double b) { return a + expr::lit(b); }
expr operator+(double a, const expr& b) { return expr::lit(a) + b; }
expr operator-(const expr& a, double b) { return a - expr::lit(b); }
expr operator-(double a, const expr& b) { return expr::lit(a) - b; }
expr operator*(const expr& a, double b) { return a * expr::lit(b); }
expr operator*(double a, const expr& b) { return expr::lit(a) * b; }
expr operator/(const expr& a, double b) { return a / expr::lit(b); }
expr operator/(double a, const expr& b) { return expr::lit(a) / b; }
expr sqrt(const expr& a) { return mk("sqrt(" + a.str() + ")"); }
expr exp(const expr& a) { return mk("exp(" + a.str() + ")"); }
expr log(const expr& a) { return mk("log(" + a.str() + ")"); }
expr tanh(const expr& a) { return mk("tanh(" + a.str() + ")"); }
expr abs(const expr& a) { return mk("abs(" + a.str() + ")"); }
expr min(const expr& a, const expr& b) {
  return mk("minimum(" + a.str() + ", " + b.str() + ")");
}
expr max(const expr& a, const expr& b) {
  return mk("maximum(" + a.str() + ", " + b.str() + ")");
}
expr pow(const expr& a, const expr& b) {
  return mk("power(" + a.str() + ", " + b.str() + ")");
}

const expr x0 = expr::arg(0);
const expr x1 = expr::arg(1);
const expr x2 = expr::arg(2);
const expr x3 = expr::arg(3);
}  // namespace thp
