// Multi-process SPMD bridge demo: the C++ analog of
// tests/multihost_worker.py.  Each OS process constructs a
// thp::session with the SAME coordinator (thp::distributed) and runs
// the SAME program in the same order — the reference's MPI-rank
// discipline (mhp/global.hpp:24-28, mpiexec -n {1..4} suites) carried
// to the embedded JAX runtime over jax.distributed.
//
// Usage: bridge_mp_demo <pid> <nproc> <port>
// The Makefile's bridge-mp-test target launches 2 processes and
// requires both to exit 0.  Checks are a local macro, NOT assert():
// python3-config's cflags define NDEBUG, which would compile assert
// away and turn this into a smoke test that can't fail.
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>

#include "thp_bridge.hpp"

namespace {
int failures = 0;
#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,   \
                   __LINE__, #cond);                                  \
      ++failures;                                                     \
    }                                                                 \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr, "usage: %s <pid> <nproc> <port>\n", argv[0]);
    return 2;
  }
  int pid = std::atoi(argv[1]);
  int nproc = std::atoi(argv[2]);
  thp::distributed d;
  d.coordinator = std::string("localhost:") + argv[3];
  d.num_processes = nproc;
  d.process_id = pid;
  d.ncpu_devices = 1;  // one virtual CPU device per process
  thp::session s(d);
  CHECK((int)s.nprocs() == nproc);

  // every collective result must be valid on EVERY process
  std::size_t n = 4 * (std::size_t)nproc;
  thp::vector v = s.make_vector(n);
  v.iota(1.0);
  double total = v.reduce();
  CHECK(total == (double)n * (n + 1) / 2.0);

  thp::vector w = s.make_vector(n);
  w.fill(2.0);
  double dp = s.dot(v, w);
  CHECK(dp == 2.0 * total);

  // op DSL across the process boundary
  thp::vector out = s.make_vector(n);
  s.transform(v, out, thp::x0 * 2.0 + 1.0);
  std::vector<double> host = out.to_host();
  CHECK(host.size() == n);
  for (std::size_t i = 0; i < n && i < host.size(); ++i)
    CHECK(host[i] == 2.0 * (double)(i + 1) + 1.0);

  // distributed sample sort exercises all_gather + all_to_all over DCN
  thp::vector keys = s.make_vector(n);
  s.transform(v, keys, 0.0 - thp::x0);  // descending values
  s.sort(keys);
  std::vector<double> sorted = keys.to_host();
  for (std::size_t i = 1; i < sorted.size(); ++i)
    CHECK(sorted[i - 1] <= sorted[i]);
  CHECK(s.is_sorted(keys));

  // typed container across processes: int32 device dtype
  thp::vector iv = s.make_vector(n, 0, 0, false, thp::dtype::i32);
  iv.iota(0.0);
  CHECK(iv.element_dtype() == thp::dtype::i32);
  CHECK(iv.reduce() == (double)(n * (n - 1) / 2));

  // round 5 across REAL process boundaries: a windowed sort (the
  // window-coordinate program) and an uneven-teams container
  thp::vector wv = s.make_vector(n);
  s.transform(v, wv, 0.0 - thp::x0);  // descending again
  s.sort(wv, 1, n - 1);               // window leaves the ends alone
  std::vector<double> wh = wv.to_host();
  CHECK(wh[0] == -1.0 && wh[n - 1] == -(double)n);
  for (std::size_t i = 2; i + 1 < n; ++i) CHECK(wh[i - 1] <= wh[i]);
  CHECK(s.is_sorted(wv, 1, n - 1));
  std::vector<std::size_t> sizes((std::size_t)nproc, 0);
  sizes[0] = n - 1;
  sizes[(std::size_t)nproc - 1] += 1;
  thp::vector uv = s.make_vector_blocks(sizes);
  uv.iota(1.0);
  CHECK(uv.reduce() == (double)n * (n + 1) / 2.0);

  if (failures) {
    std::fprintf(stderr, "bridge_mp_demo pid=%d/%d: %d FAILURES\n", pid,
                 nproc, failures);
    return 1;
  }
  std::printf("bridge_mp_demo pid=%d/%d: PASSED\n", pid, nproc);
  return 0;
}
