// bridge_demo: C++ program driving the TPU backend end-to-end —
// the native equivalent of examples/stencil_1d.py + dot_product.py.
// Usage: bridge_demo [ncpu_devices]  (0 = real device platform)
#include <cmath>
#include <cstdio>
#include <cstdlib>

#include "thp_bridge.hpp"

int main(int argc, char** argv) {
  int ncpu = argc > 1 ? std::atoi(argv[1]) : 8;
  thp::session s(ncpu);
  std::printf("nprocs=%zu\n", s.nprocs());

  const std::size_t n = 1 << 14;

  // iota + reduce
  thp::vector a = s.make_vector(n);
  a.iota(1.0);
  double sum = a.reduce();
  double expect = 0.5 * (double)n * (double)(n + 1);
  if (std::abs(sum - expect) > 1e-3 * expect) {
    std::printf("reduce FAIL: %f vs %f\n", sum, expect);
    return 1;
  }

  // dot product
  thp::vector b = s.make_vector(n);
  b.fill(2.0);
  double d = s.dot(a, b);
  if (std::abs(d - 2.0 * expect) > 1e-3 * 2.0 * expect) {
    std::printf("dot FAIL: %f vs %f\n", d, 2.0 * expect);
    return 1;
  }

  // halo'd stencil, 4 fused steps on device
  thp::vector x = s.make_vector(n, 1, 1, false);
  thp::vector y = s.make_vector(n, 1, 1, false);
  x.iota(0.0);
  y.iota(0.0);
  s.stencil_iterate(x, y, {1.0 / 3, 1.0 / 3, 1.0 / 3}, 4);
  auto host = x.to_host();
  // iota is a fixed point of the mean stencil in the interior
  for (std::size_t i = 8; i < n - 8; i += n / 7)
    if (std::abs(host[i] - (double)i) > 1e-2) {
      std::printf("stencil FAIL at %zu: %f\n", i, host[i]);
      return 1;
    }

  std::printf("bridge demo PASSED (n=%zu, sum=%.0f, dot=%.0f)\n", n, sum,
              d);
  return 0;
}
