// bridge_demo: C++ program driving the TPU backend end-to-end — the
// native equivalent of the reference's example set (vector-add,
// dot_product, stencil-1d, inclusive_scan, gemv, transpose) asserted
// against serial C++ oracles (the reference's oracle pattern,
// test/gtest/include/common-tests.hpp:52-81).
// Usage: bridge_demo [ncpu_devices]  (0 = real device platform)
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <unistd.h>
#include <vector>

#include "thp_bridge.hpp"

namespace {

int failures = 0;

void check_close(const char* what, double got, double want,
                 double tol = 1e-4) {
  double scale = std::abs(want) > 1.0 ? std::abs(want) : 1.0;
  if (std::abs(got - want) > tol * scale) {
    std::printf("%s FAIL: got %.8g want %.8g\n", what, got, want);
    ++failures;
  }
}

void check_range(const char* what, const std::vector<double>& got,
                 const std::vector<double>& want, double tol = 1e-4) {
  if (got.size() != want.size()) {
    std::printf("%s FAIL: size %zu vs %zu\n", what, got.size(),
                want.size());
    ++failures;
    return;
  }
  for (std::size_t i = 0; i < got.size(); ++i) {
    double scale = std::abs(want[i]) > 1.0 ? std::abs(want[i]) : 1.0;
    if (std::abs(got[i] - want[i]) > tol * scale) {
      std::printf("%s FAIL at %zu: got %.8g want %.8g\n", what, i,
                  got[i], want[i]);
      ++failures;
      return;
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  // expr DSL serialization: canonical strings ARE the op cache keys
  // (equal strings -> one Python callable -> reused XLA programs), so
  // their exact shape is part of the bridge contract
  if ((thp::x0 * 2.0 + 1.0).str() != "((x0 * 2) + 1)" ||
      thp::max(thp::sqrt(thp::abs(thp::x0)), thp::x1).str() !=
          "maximum(sqrt(abs(x0)), x1)" ||
      (1.5 / thp::x2 - -thp::x3).str() != "((1.5 / x2) - (0 - x3))") {
    std::printf("expr serialization FAIL: %s | %s | %s\n",
                (thp::x0 * 2.0 + 1.0).str().c_str(),
                thp::max(thp::sqrt(thp::abs(thp::x0)), thp::x1)
                    .str().c_str(),
                (1.5 / thp::x2 - -thp::x3).str().c_str());
    return 1;
  }

  int ncpu = argc > 1 ? std::atoi(argv[1]) : 8;
  thp::session s(ncpu);
  std::printf("nprocs=%zu\n", s.nprocs());

  const std::size_t n = 1 << 14;

  // ---- iota + reduce --------------------------------------------------
  thp::vector a = s.make_vector(n);
  a.iota(1.0);
  double expect = 0.5 * (double)n * (double)(n + 1);
  check_close("reduce", a.reduce(), expect);

  // ---- dot ------------------------------------------------------------
  thp::vector b = s.make_vector(n);
  b.fill(2.0);
  check_close("dot", s.dot(a, b), 2.0 * expect);

  // ---- vector-add via the zip op DSL (examples/mhp/vector-add.cpp) ----
  thp::vector vsum = s.make_vector(n);
  s.transform2(a, b, vsum, thp::x0 + thp::x1);
  check_close("vector-add reduce", vsum.reduce(), expect + 2.0 * n);

  // ---- unary transform + for_each DSL ---------------------------------
  thp::vector t = s.make_vector(n);
  s.transform(a, t, thp::x0 * 2.0 + 1.0);     // 2*i + 1
  check_close("transform reduce", t.reduce(), 2.0 * expect + n);
  s.for_each(t, thp::sqrt(thp::abs(thp::x0 - 1.0) / 2.0));  // back to
  // sqrt(i): sum over i=1..n of sqrt(i)
  {
    double want = 0.0;
    for (std::size_t i = 1; i <= n; ++i) want += std::sqrt((double)i);
    check_close("for_each reduce", t.reduce(), want);
  }

  // ---- transform_reduce (the driver metric workload) ------------------
  check_close("transform_reduce x^2",
              s.transform_reduce(b, thp::x0 * thp::x0), 4.0 * n);

  // ---- inclusive / exclusive scan -------------------------------------
  thp::vector sc = s.make_vector(n);
  s.inclusive_scan(a, sc);            // scan of 1..n: i*(i+1)/2
  {
    auto host = sc.to_host();
    std::vector<double> want(n);
    for (std::size_t i = 0; i < n; ++i)
      want[i] = 0.5 * (double)(i + 1) * (double)(i + 2);
    check_range("inclusive_scan", host, want);
  }
  s.exclusive_scan(a, sc, 10.0);      // 10 + i*(i+1)/2 shifted
  {
    auto host = sc.to_host();
    std::vector<double> want(n);
    double run = 10.0;
    for (std::size_t i = 0; i < n; ++i) {
      want[i] = run;
      run += (double)(i + 1);
    }
    check_range("exclusive_scan", host, want);
  }
  {
    // round 5: MISMATCHED in/out windows (the Python layer realigns
    // window-coordinate results with one masked all_to_all)
    const std::size_t wn = 96;
    thp::vector wi = s.make_vector(wn);
    thp::vector wo = s.make_vector(wn);
    wi.iota(1.0);
    wo.fill(-1.0);
    s.inclusive_scan(wi, 0, 50, wo, 7, 57);
    auto host = wo.to_host();
    std::vector<double> want(wn, -1.0);
    double run = 0.0;
    for (std::size_t i = 0; i < 50; ++i) {
      run += (double)(i + 1);
      want[7 + i] = run;
    }
    check_range("inclusive_scan windows", host, want);
    s.exclusive_scan(wi, 10, 40, wo, 0, 30, 5.0);
    host = wo.to_host();
    run = 5.0;
    for (std::size_t i = 0; i < 30; ++i) {
      want[i] = run;
      run += (double)(10 + i + 1);
    }
    check_range("exclusive_scan windows", host, want);
  }
  {
    // round 5: uneven block distribution (teams) from C++ — shard 0
    // owns 10, shard 1 owns 0 (empty team), the rest splits the tail
    std::size_t P = s.nprocs();
    std::vector<std::size_t> sizes(P, 0);
    const std::size_t un = 57;
    sizes[0] = 10;
    if (P > 2) {
      std::size_t rest = un - 10, each = rest / (P - 2);
      for (std::size_t r = 2; r < P; ++r) sizes[r] = each;
      sizes[P - 1] += rest - each * (P - 2);
    } else {
      sizes[P - 1] += un - 10;
    }
    thp::vector uv = s.make_vector_blocks(sizes);
    uv.iota(1.0);
    check_close("uneven reduce", uv.reduce(),
                0.5 * (double)un * (double)(un + 1));
    s.sort(uv, /*descending=*/true);
    auto host = uv.to_host();
    std::vector<double> want(un);
    for (std::size_t i = 0; i < un; ++i) want[i] = (double)(un - i);
    check_range("uneven sort desc", host, want);
    thp::vector us = s.make_vector_blocks(sizes);
    s.inclusive_scan(uv, us);  // scan of un..1
    host = us.to_host();
    double run = 0.0;
    for (std::size_t i = 0; i < un; ++i) {
      run += (double)(un - i);
      want[i] = run;
    }
    check_range("uneven scan", host, want);
  }

  // ---- distributed sample sort ----------------------------------------
  thp::vector sv = s.make_vector(n);
  sv.iota(0.0);
  s.for_each(sv, 0.0 - thp::x0);      // n descending values -0..-(n-1)
  s.sort(sv);
  {
    auto host = sv.to_host();
    std::vector<double> want(n);
    for (std::size_t i = 0; i < n; ++i)
      want[i] = -(double)(n - 1 - i);
    check_range("sort ascending", host, want);
  }
  s.sort(sv, /*descending=*/true);
  {
    auto host = sv.to_host();
    std::vector<double> want(n);
    for (std::size_t i = 0; i < n; ++i) want[i] = -(double)i;
    check_range("sort descending", host, want);
  }
  // key-value: keys descending 0..-(n-1) after the sort above; payload
  // iota must come out reversed when keys are sorted ascending
  thp::vector pv = s.make_vector(n);
  pv.iota(0.0);
  s.sort_by_key(sv, pv);
  {
    auto hk = sv.to_host();
    auto hp = pv.to_host();
    std::vector<double> wk(n), wp(n);
    for (std::size_t i = 0; i < n; ++i) {
      wk[i] = -(double)(n - 1 - i);
      wp[i] = (double)(n - 1 - i);
    }
    check_range("sort_by_key keys", hk, wk);
    check_range("sort_by_key payload", hp, wp);
  }
  // after sort_by_key the keys are ascending and the payload is the
  // reversed iota: one true case, one false case
  if (!s.is_sorted(sv)) {
    std::fprintf(stderr, "FAIL is_sorted: ascending keys read unsorted\n");
    return 1;
  }
  if (s.is_sorted(pv)) {
    std::fprintf(stderr, "FAIL is_sorted: reversed payload read sorted\n");
    return 1;
  }
  // ---- round 5: subrange-window sort family from C++ ------------------
  {
    const std::size_t wn = 64;
    thp::vector wv = s.make_vector(wn);
    wv.iota(0.0);
    s.sort(wv, 5, 40, /*descending=*/true);  // window descending
    auto host = wv.to_host();
    std::vector<double> want(wn);
    for (std::size_t i = 0; i < wn; ++i) want[i] = (double)i;
    for (std::size_t i = 5; i < 40; ++i) want[i] = (double)(44 - i);
    check_range("sort window desc", host, want);
    if (s.is_sorted(wv, 5, 40)) {
      std::printf("is_sorted window FAIL: descending read sorted\n");
      ++failures;
    }
    if (!s.is_sorted(wv, 40, wn)) {
      std::printf("is_sorted window FAIL: ascending tail\n");
      ++failures;
    }
    // overlapping key/value windows of ONE vector (payload-last blend)
    thp::vector ov = s.make_vector(wn);
    ov.iota(0.0);
    s.for_each(ov, thp::x0 * -1.0);  // descending data
    auto before = ov.to_host();
    s.sort_by_key(ov, 0, 20, ov, 10, 30);
    auto after = ov.to_host();
    std::vector<double> wantv = before;
    // keys [0,20) ascending; ties impossible; payload [10,30) follows
    std::vector<std::size_t> order(20);
    for (std::size_t i = 0; i < 20; ++i) order[i] = 19 - i;  // reversed
    for (std::size_t i = 0; i < 20; ++i)
      wantv[i] = before[order[i]];
    for (std::size_t i = 0; i < 20; ++i)
      wantv[10 + i] = before[10 + order[i]];
    check_range("sort_by_key overlap windows", after, wantv);
  }
  {
    // argsort of the (now ascending) keys is the identity permutation
    thp::vector perm = s.argsort(sv);
    auto host = perm.to_host();
    std::vector<double> want(n);
    for (std::size_t i = 0; i < n; ++i) want[i] = (double)i;
    check_range("argsort identity", host, want);
  }

  // ---- halo'd stencil, 4 fused steps on device ------------------------
  thp::vector x = s.make_vector(n, 1, 1, false);
  thp::vector y = s.make_vector(n, 1, 1, false);
  x.iota(0.0);
  y.iota(0.0);
  s.stencil_iterate(x, y, {1.0 / 3, 1.0 / 3, 1.0 / 3}, 4);
  {
    auto host = x.to_host();
    // iota is a fixed point of the mean stencil in the interior
    for (std::size_t i = 8; i < n - 8; i += n / 7)
      check_close("stencil interior", host[i], (double)i, 1e-2);
  }

  // ---- sparse gemv (examples/shp/gemv_example.cpp) --------------------
  {
    const std::size_t m = 1024;
    std::vector<std::int64_t> ri, ci;
    std::vector<double> vv;
    for (std::size_t i = 0; i < m; ++i)
      for (std::int64_t dj = -1; dj <= 1; ++dj) {
        std::int64_t j = (std::int64_t)i + dj;
        if (j < 0 || j >= (std::int64_t)m) continue;
        ri.push_back((std::int64_t)i);
        ci.push_back(j);
        vv.push_back(1.0 + 0.001 * (double)i + 0.01 * (double)dj);
      }
    thp::sparse_matrix A = s.make_sparse_coo(m, m, ri, ci, vv);
    thp::vector bv = s.make_vector(m);
    thp::vector cv = s.make_vector(m);
    bv.iota(1.0);
    cv.fill(0.5);
    s.gemv(cv, A, bv);  // c += A·b
    std::vector<double> want(m, 0.5);
    for (std::size_t k = 0; k < vv.size(); ++k)
      want[(std::size_t)ri[k]] += vv[k] * (double)(ci[k] + 1);
    check_range("gemv", cv.to_host(), want);
  }

  // ---- dense gemm ------------------------------------------------------
  {
    const std::size_t m = 96, k = 64, p = 80;
    std::vector<double> da(m * k), db(k * p);
    for (std::size_t i = 0; i < da.size(); ++i)
      da[i] = 0.01 * (double)(i % 37) - 0.1;
    for (std::size_t i = 0; i < db.size(); ++i)
      db[i] = 0.02 * (double)(i % 29) - 0.2;
    thp::dense_matrix A = s.make_dense(m, k, da);
    thp::dense_matrix B = s.make_dense(k, p, db);
    thp::dense_matrix C = s.make_dense(m, p);
    s.gemm(A, B, C);
    std::vector<double> want(m * p, 0.0);
    for (std::size_t i = 0; i < m; ++i)
      for (std::size_t kk = 0; kk < k; ++kk)
        for (std::size_t j = 0; j < p; ++j)
          want[i * p + j] += da[i * k + kk] * db[kk * p + j];
    check_range("gemm", C.to_host(), want, 1e-3);
  }

  // ---- mdarray transpose (examples/mhp/transpose-cpu.cpp) -------------
  {
    const std::size_t m = 64, p = 48;
    std::vector<double> dm(m * p);
    for (std::size_t i = 0; i < dm.size(); ++i)
      dm[i] = (double)i * 0.5 - 3.0;
    thp::mdarray M = s.make_mdarray(m, p, dm);
    thp::mdarray T = s.make_mdarray(p, m);
    s.transpose(T, M);
    std::vector<double> want(p * m);
    for (std::size_t i = 0; i < m; ++i)
      for (std::size_t j = 0; j < p; ++j)
        want[j * m + i] = dm[i * p + j];
    check_range("transpose", T.to_host(), want);
  }

  // ---- N-D mdarray: 3-D axis-permutation transpose + submdspan
  // (round 5 — the spec'd N-D surface reached from C++,
  // doc/spec/source/containers/distributed_mdarray.rst:12-23) --------
  {
    const std::size_t a = 12, b = 10, c = 8;
    std::vector<double> d3(a * b * c);
    for (std::size_t i = 0; i < d3.size(); ++i)
      d3[i] = (double)i * 0.25 - 40.0;
    thp::mdarray M = s.make_mdarray({a, b, c}, d3);
    if (M.rank() != 3 || M.shape()[1] != b) {
      std::printf("mdarray3d shape FAIL\n");
      ++failures;
    }
    check_range("mdarray3d roundtrip", M.to_host(), d3);
    // permute (a,b,c) -> (c,a,b) via axes {2,0,1}
    thp::mdarray T3 = s.make_mdarray({c, a, b});
    s.transpose(T3, M, {2, 0, 1});
    std::vector<double> want3(c * a * b);
    for (std::size_t i = 0; i < a; ++i)
      for (std::size_t j = 0; j < b; ++j)
        for (std::size_t k3 = 0; k3 < c; ++k3)
          want3[(k3 * a + i) * b + j] = d3[(i * b + j) * c + k3];
    check_range("transpose3d axes(2,0,1)", T3.to_host(), want3);
    // default (reversed) permutation on the same 3-D array
    thp::mdarray TR = s.make_mdarray({c, b, a});
    s.transpose(TR, M);
    std::vector<double> wantr(c * b * a);
    for (std::size_t i = 0; i < a; ++i)
      for (std::size_t j = 0; j < b; ++j)
        for (std::size_t k3 = 0; k3 < c; ++k3)
          wantr[(k3 * b + j) * a + i] = d3[(i * b + j) * c + k3];
    check_range("transpose3d reversed", TR.to_host(), wantr);
    // submdspan window [2,9) x [1,6) x [3,8): materializes ONLY the
    // window, row-major over the window shape
    thp::mdspan W = s.submdspan(M, {{2, 9}, {1, 6}, {3, 8}});
    if (W.rank() != 3 || W.shape()[0] != 7 || W.shape()[1] != 5 ||
        W.shape()[2] != 5) {
      std::printf("submdspan shape FAIL\n");
      ++failures;
    }
    std::vector<double> wantw(7 * 5 * 5);
    for (std::size_t i = 0; i < 7; ++i)
      for (std::size_t j = 0; j < 5; ++j)
        for (std::size_t k3 = 0; k3 < 5; ++k3)
          wantw[(i * 5 + j) * 5 + k3] =
              d3[((i + 2) * b + (j + 1)) * c + (k3 + 3)];
    check_range("submdspan3d", W.to_host(), wantw);
  }

  // ---- checkpoint round-trip ------------------------------------------
  {
    thp::vector v = s.make_vector(777);
    v.iota(3.0);
    char ckpt[64];
    std::snprintf(ckpt, sizeof ckpt, "/tmp/thp_bridge_ckpt_%ld.npz",
                  (long)getpid());
    s.save(ckpt, v);
    thp::vector w = s.load_vector(ckpt);
    std::remove(ckpt);
    if (w.size() != 777) {
      std::printf("checkpoint FAIL: size %zu\n", w.size());
      ++failures;
    } else {
      check_range("checkpoint", w.to_host(), v.to_host());
    }
  }

  {
    // custom-op escape hatch (round 4; SURVEY §7 hard-part 2 option
    // b): conditionals are outside the arithmetic DSL — leaky relu
    // needs jnp.where, expressible only as traceable Python source
    thp::custom_op leaky{"lambda x0: jnp.where(x0 > 0, x0, 0.01 * x0)",
                         1};
    thp::vector cin = s.make_vector(64);
    thp::vector cout = s.make_vector(64);
    cin.iota(-32.0);  // half negative, half positive
    s.transform(cin, cout, leaky);
    std::vector<double> ch = cout.to_host();
    for (std::size_t i = 0; i < ch.size(); ++i) {
      double x = -32.0 + (double)i;
      double want = x > 0 ? x : 0.01 * x;
      if (std::abs(ch[i] - want) > 1e-5) {
        std::printf("custom op FAIL at %zu: got %g want %g\n", i, ch[i],
                    want);
        ++failures;
        break;
      }
    }
    // zipped binary custom op + custom transform_reduce
    thp::custom_op takegt{
        "lambda x0, x1: jnp.where(x0 > x1, x0, x1)", 2};
    thp::vector cz = s.make_vector(64);
    s.transform2(cin, cout, cz, takegt);
    check_close("custom zip reduce", cz.reduce(), [&] {
      double acc = 0;
      for (std::size_t i = 0; i < ch.size(); ++i) {
        double x = -32.0 + (double)i;
        acc += x > ch[i] ? x : ch[i];
      }
      return acc;
    }());
    thp::custom_op clip6{"lambda x0: jnp.clip(x0, 0.0, 6.0)", 1};
    double clipped = s.transform_reduce(cin, clip6);
    check_close("custom transform_reduce", clipped, [&] {
      double acc = 0;
      for (std::size_t i = 0; i < 64; ++i) {
        double x = -32.0 + (double)i;
        acc += x < 0 ? 0.0 : (x > 6 ? 6.0 : x);
      }
      return acc;
    }());
  }

  {
    // typed containers (round 4): the device dtype is selectable —
    // f32 stays the default (what earlier bridge versions allocated);
    // i32 holds exact integers through iota/reduce/to_host
    thp::vector f = s.make_vector(64);
    if (f.element_dtype() != thp::dtype::f32) {
      std::printf("dtype FAIL: default is not f32\n");
      ++failures;
    }
    thp::vector iv = s.make_vector(100, 0, 0, false, thp::dtype::i32);
    iv.iota(0.0);
    check_close("i32 reduce", iv.reduce(), 100.0 * 99.0 / 2.0);
    std::vector<double> ih = iv.to_host();
    if (ih.size() != 100 || ih[7] != 7.0 || ih[99] != 99.0) {
      std::printf("i32 to_host FAIL\n");
      ++failures;
    }
  }

  if (failures) {
    std::printf("bridge demo: %d FAILURES\n", failures);
    return 1;
  }
  std::printf("bridge demo PASSED (n=%zu, all surfaces)\n", n);
  return 0;
}
