// thp_bridge implementation: CPython embedding of the dr_tpu runtime.
#include "thp_bridge.hpp"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdlib>
#include <stdexcept>

namespace thp {

namespace {

[[noreturn]] void fail(const char* what) {
  if (PyErr_Occurred()) PyErr_Print();
  throw std::runtime_error(std::string("thp_bridge: ") + what);
}

PyObject* must(PyObject* p, const char* what) {
  if (!p) fail(what);
  return p;
}

}  // namespace

struct session::impl {
  PyObject* dr = nullptr;        // module dr_tpu
  PyObject* stencil_mod = nullptr;
  bool owns_interpreter = false;
};

session::session(int ncpu_devices) : impl_(new impl) {
  if (!Py_IsInitialized()) {
    if (ncpu_devices > 0) {
      std::string flags = "--xla_force_host_platform_device_count=" +
                          std::to_string(ncpu_devices);
      setenv("XLA_FLAGS", flags.c_str(), 1);
    }
    Py_InitializeEx(0);
    impl_->owns_interpreter = true;
  }
  if (ncpu_devices > 0) {
    // env alone is not enough if site customization imported jax already
    if (PyRun_SimpleString(
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"))
      fail("forcing cpu platform");
  }
  impl_->dr = must(PyImport_ImportModule("dr_tpu"), "import dr_tpu");
  must(PyObject_CallMethod(impl_->dr, "init", nullptr), "dr_tpu.init()");
  impl_->stencil_mod = must(
      PyImport_ImportModule("dr_tpu.algorithms.stencil"),
      "import dr_tpu.algorithms.stencil");
  // XLA device-count flags are frozen at first interpreter/backend init,
  // so a later session cannot change the mesh size — fail loudly instead
  // of computing over the wrong partitioning
  if (ncpu_devices > 0 && nprocs() != (std::size_t)ncpu_devices)
    fail("requested virtual mesh size differs from the initialized "
         "backend; device-count flags are fixed at first init");
}

session::~session() {
  Py_XDECREF(impl_->stencil_mod);
  Py_XDECREF(impl_->dr);
  // keep the interpreter alive: other sessions/objects may still use it
}

std::size_t session::nprocs() const {
  PyObject* r = must(PyObject_CallMethod(impl_->dr, "nprocs", nullptr),
                     "nprocs()");
  std::size_t n = PyLong_AsSize_t(r);
  Py_DECREF(r);
  return n;
}

void session::exec(const std::string& code) {
  if (PyRun_SimpleString(code.c_str())) fail("exec");
}

vector session::make_vector(std::size_t n, std::size_t prev,
                            std::size_t next, bool periodic) {
  PyObject* hb = nullptr;
  if (prev || next) {
    PyObject* hb_cls = must(
        PyObject_GetAttrString(impl_->dr, "halo_bounds"), "halo_bounds");
    hb = must(PyObject_CallFunction(hb_cls, "nnO", (Py_ssize_t)prev,
                                    (Py_ssize_t)next,
                                    periodic ? Py_True : Py_False),
              "halo_bounds(...)");
    Py_DECREF(hb_cls);
  }
  PyObject* cls = must(
      PyObject_GetAttrString(impl_->dr, "distributed_vector"),
      "distributed_vector");
  PyObject* obj;
  if (hb) {
    PyObject* args = Py_BuildValue("(n)", (Py_ssize_t)n);
    PyObject* kwargs = Py_BuildValue("{s:O}", "halo", hb);
    obj = must(PyObject_Call(cls, args, kwargs), "distributed_vector(...)");
    Py_DECREF(args);
    Py_DECREF(kwargs);
    Py_DECREF(hb);
  } else {
    obj = must(PyObject_CallFunction(cls, "n", (Py_ssize_t)n),
               "distributed_vector(n)");
  }
  Py_DECREF(cls);
  return vector(this, obj, n);
}

double session::dot(const vector& a, const vector& b) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "dot", "OO",
                          (PyObject*)a.obj_, (PyObject*)b.obj_),
      "dot(a, b)");
  double v = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return v;
}

void session::stencil_iterate(vector& a, vector& b,
                              const std::vector<double>& weights,
                              int steps) {
  PyObject* w = PyList_New((Py_ssize_t)weights.size());
  for (Py_ssize_t i = 0; i < (Py_ssize_t)weights.size(); ++i)
    PyList_SetItem(w, i, PyFloat_FromDouble(weights[i]));
  PyObject* r = must(
      PyObject_CallMethod(impl_->stencil_mod, "stencil_iterate", "OOOi",
                          (PyObject*)a.obj_, (PyObject*)b.obj_, w, steps),
      "stencil_iterate");
  // stencil_iterate returns the buffer holding the final state; callers
  // keep using `a` as "current" — swap handles if needed
  if (r == (PyObject*)b.obj_) std::swap(a.obj_, b.obj_);
  Py_DECREF(r);
  Py_DECREF(w);
}

vector::~vector() { Py_XDECREF((PyObject*)obj_); }

vector::vector(vector&& o) noexcept
    : sess_(o.sess_), obj_(o.obj_), n_(o.n_) {
  o.obj_ = nullptr;
}

vector& vector::operator=(vector&& o) noexcept {
  if (this != &o) {
    Py_XDECREF((PyObject*)obj_);
    sess_ = o.sess_;
    obj_ = o.obj_;
    n_ = o.n_;
    o.obj_ = nullptr;
  }
  return *this;
}

void vector::iota(double start) {
  PyObject* r = must(
      PyObject_CallMethod(sess_->impl_->dr, "iota", "Od",
                          (PyObject*)obj_, start),
      "iota");
  Py_DECREF(r);
}

void vector::fill(double value) {
  PyObject* r = must(
      PyObject_CallMethod(sess_->impl_->dr, "fill", "Od",
                          (PyObject*)obj_, value),
      "fill");
  Py_DECREF(r);
}

double vector::reduce() const {
  PyObject* r = must(
      PyObject_CallMethod(sess_->impl_->dr, "reduce", "O",
                          (PyObject*)obj_),
      "reduce");
  double v = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return v;
}

void vector::halo_exchange() {
  PyObject* h = must(
      PyObject_CallMethod(sess_->impl_->dr, "halo", "O", (PyObject*)obj_),
      "halo(v)");
  PyObject* r = must(PyObject_CallMethod(h, "exchange", nullptr),
                     "exchange()");
  Py_DECREF(r);
  Py_DECREF(h);
}

std::vector<double> vector::to_host() const {
  PyObject* arr = must(
      PyObject_CallMethod(sess_->impl_->dr, "to_numpy", "O",
                          (PyObject*)obj_),
      "to_numpy");
  PyObject* lst = must(PyObject_CallMethod(arr, "tolist", nullptr),
                       "tolist");
  std::vector<double> out;
  Py_ssize_t n = PyList_Size(lst);
  out.reserve((std::size_t)n);
  for (Py_ssize_t i = 0; i < n; ++i)
    out.push_back(PyFloat_AsDouble(PyList_GetItem(lst, i)));
  Py_DECREF(lst);
  Py_DECREF(arr);
  return out;
}

}  // namespace thp
