// thp_bridge implementation: CPython embedding of the dr_tpu runtime.
#include "thp_bridge.hpp"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <stdexcept>

namespace thp {

namespace {

[[noreturn]] void fail(const char* what) {
  if (PyErr_Occurred()) PyErr_Print();
  throw std::runtime_error(std::string("thp_bridge: ") + what);
}

PyObject* must(PyObject* p, const char* what) {
  if (!p) fail(what);
  return p;
}

// numpy view over host memory (no element boxing); the caller's buffer
// must outlive uses of the returned array — every call site here copies
// into a container/device layout before returning.
PyObject* np_view(PyObject* np, const void* data, std::size_t nbytes,
                  const char* dtype) {
  PyObject* mv = must(
      PyMemoryView_FromMemory(
          const_cast<char*>(static_cast<const char*>(data)),
          (Py_ssize_t)nbytes, PyBUF_READ),
      "memoryview");
  PyObject* arr = must(PyObject_CallMethod(np, "frombuffer", "Os", mv,
                                           dtype),
                       "np.frombuffer");
  Py_DECREF(mv);
  return arr;
}

}  // namespace

// ---------------------------------------------------------------------
// session impl
// ---------------------------------------------------------------------
struct session::impl {
  PyObject* dr = nullptr;        // module dr_tpu
  PyObject* views = nullptr;     // module dr_tpu.views.views
  PyObject* stencil_mod = nullptr;
  PyObject* expr_mod = nullptr;  // module dr_tpu.utils.expr
  PyObject* np = nullptr;        // module numpy
  bool owns_interpreter = false;
  // JAX x64 state, re-read per query (a cheap attribute read): with
  // x64 disabled (the default) a float64 device buffer silently
  // becomes f32, so make_vector(dtype::f64) must fail loudly instead
  // (ADVICE r4).  Not cached — the embedder can legitimately flip
  // jax_enable_x64 via session::exec between calls.
  bool x64_enabled() {
    PyObject* jax = must(PyImport_ImportModule("jax"), "import jax");
    PyObject* cfg = must(PyObject_GetAttrString(jax, "config"),
                         "jax.config");
    PyObject* v = must(PyObject_GetAttrString(cfg, "jax_enable_x64"),
                       "jax_enable_x64");
    bool on = PyObject_IsTrue(v) == 1;
    Py_DECREF(v);
    Py_DECREF(cfg);
    Py_DECREF(jax);
    return on;
  }

  // op DSL -> cached jax callable (cache lives Python-side, keyed by
  // the canonical string, so equal exprs share one function object)
  PyObject* op(const expr& e, int nargs) {
    return must(PyObject_CallMethod(expr_mod, "op_from_expr", "si",
                                    e.str().c_str(), nargs),
                "op_from_expr");
  }

  // custom-op escape hatch -> cached jax callable (full Python source;
  // same trust boundary as session::exec)
  PyObject* op(const custom_op& e) {
    return must(PyObject_CallMethod(expr_mod, "op_from_source", "si",
                                    e.source.c_str(), e.nargs),
                "op_from_source");
  }

  // f64 host view -> f32 numpy array (device dtype)
  PyObject* np_f32(const std::vector<double>& v) {
    PyObject* raw = np_view(np, v.data(), v.size() * sizeof(double),
                            "float64");
    PyObject* arr = must(PyObject_CallMethod(raw, "astype", "s",
                                             "float32"),
                         "astype(float32)");
    Py_DECREF(raw);
    return arr;
  }

  PyObject* np_i64(const std::vector<std::int64_t>& v) {
    PyObject* raw = np_view(np, v.data(), v.size() * sizeof(std::int64_t),
                            "int64");
    // copy so the container owns its memory beyond this call
    PyObject* arr = must(PyObject_CallMethod(raw, "copy", nullptr),
                         "np.copy");
    Py_DECREF(raw);
    return arr;
  }

  // shared interpreter boot: XLA device-count flags must be in the env
  // before the first interpreter/backend init; CPU forcing must go
  // through jax.config (the env var alone is frozen by any site
  // customization that already imported jax)
  void boot(int ncpu_devices) {
    if (!Py_IsInitialized()) {
      if (ncpu_devices > 0) {
        std::string flags = "--xla_force_host_platform_device_count=" +
                            std::to_string(ncpu_devices);
        setenv("XLA_FLAGS", flags.c_str(), 1);
      }
      Py_InitializeEx(0);
      owns_interpreter = true;
    }
    if (ncpu_devices > 0) {
      if (PyRun_SimpleString(
              "import jax\n"
              "jax.config.update('jax_platforms', 'cpu')\n"))
        fail("forcing cpu platform");
    }
    dr = must(PyImport_ImportModule("dr_tpu"), "import dr_tpu");
  }

  void import_modules() {
    views = must(PyImport_ImportModule("dr_tpu.views.views"),
                 "import dr_tpu.views.views");
    stencil_mod = must(
        PyImport_ImportModule("dr_tpu.algorithms.stencil"),
        "import dr_tpu.algorithms.stencil");
    expr_mod = must(PyImport_ImportModule("dr_tpu.utils.expr"),
                    "import dr_tpu.utils.expr");
    np = must(PyImport_ImportModule("numpy"), "import numpy");
  }

  // contiguous f64 copy-out of any numpy-convertible object
  std::vector<double> to_host_f64(PyObject* arr_like) {
    PyObject* asc = must(
        PyObject_CallMethod(np, "ascontiguousarray", "Os", arr_like,
                            "float64"),
        "ascontiguousarray");
    Py_buffer view;
    if (PyObject_GetBuffer(asc, &view, PyBUF_CONTIG_RO) != 0)
      fail("buffer protocol");
    std::vector<double> out((std::size_t)view.len / sizeof(double));
    std::memcpy(out.data(), view.buf, (std::size_t)view.len);
    PyBuffer_Release(&view);
    Py_DECREF(asc);
    return out;
  }
};

session::session(int ncpu_devices) : impl_(new impl) {
  impl_->boot(ncpu_devices);
  must(PyObject_CallMethod(impl_->dr, "init", nullptr), "dr_tpu.init()");
  impl_->import_modules();
  // XLA device-count flags are frozen at first interpreter/backend init,
  // so a later session cannot change the mesh size — fail loudly instead
  // of computing over the wrong partitioning
  if (ncpu_devices > 0 && nprocs() != (std::size_t)ncpu_devices)
    fail("requested virtual mesh size differs from the initialized "
         "backend; device-count flags are fixed at first init");
}

session::session(const distributed& d) : impl_(new impl) {
  // CPU multi-process testing is the supported transport here (each
  // process contributes ncpu_devices virtual CPU devices; TPU pods
  // would pass ncpu_devices = 0 and let the platform enumerate)
  impl_->boot(d.ncpu_devices);
  must(PyObject_CallMethod(impl_->dr, "init_distributed", "sii",
                           d.coordinator.c_str(), d.num_processes,
                           d.process_id),
       "dr_tpu.init_distributed(...)");
  impl_->import_modules();
  std::size_t want = (std::size_t)d.num_processes *
                     (d.ncpu_devices > 0 ? d.ncpu_devices : 1);
  if (d.ncpu_devices > 0 && nprocs() != want)
    fail("distributed mesh size differs from num_processes * "
         "ncpu_devices; device-count flags are fixed at first init");
}

session::~session() {
  Py_XDECREF(impl_->np);
  Py_XDECREF(impl_->expr_mod);
  Py_XDECREF(impl_->stencil_mod);
  Py_XDECREF(impl_->views);
  Py_XDECREF(impl_->dr);
  // keep the interpreter alive: other sessions/objects may still use it
}

std::size_t session::nprocs() const {
  PyObject* r = must(PyObject_CallMethod(impl_->dr, "nprocs", nullptr),
                     "nprocs()");
  std::size_t n = PyLong_AsSize_t(r);
  Py_DECREF(r);
  return n;
}

void session::exec(const std::string& code) {
  if (PyRun_SimpleString(code.c_str())) fail("exec");
}

// ------------------------------------------------------------ containers

namespace {
const char* np_name(dtype dt) {
  switch (dt) {
    case dtype::f32: return "float32";
    case dtype::i32: return "int32";
    default: return "float64";
  }
}
}  // namespace

vector session::make_vector(std::size_t n, std::size_t prev,
                            std::size_t next, bool periodic, dtype dt) {
  if (dt == dtype::f64 && !impl_->x64_enabled())
    fail("make_vector: dtype::f64 requested but JAX x64 is disabled — "
         "the device buffer would silently be f32 while "
         "element_dtype() reports f64; enable x64 "
         "(JAX_ENABLE_X64=1 before session construction) or use "
         "dtype::f32");
  PyObject* hb = nullptr;
  if (prev || next) {
    PyObject* hb_cls = must(
        PyObject_GetAttrString(impl_->dr, "halo_bounds"), "halo_bounds");
    hb = must(PyObject_CallFunction(hb_cls, "nnO", (Py_ssize_t)prev,
                                    (Py_ssize_t)next,
                                    periodic ? Py_True : Py_False),
              "halo_bounds(...)");
    Py_DECREF(hb_cls);
  }
  PyObject* cls = must(
      PyObject_GetAttrString(impl_->dr, "distributed_vector"),
      "distributed_vector");
  PyObject* np_dt = must(
      PyObject_GetAttrString(impl_->np, np_name(dt)), "numpy dtype");
  PyObject* args = Py_BuildValue("(n)", (Py_ssize_t)n);
  PyObject* kwargs = hb
      ? Py_BuildValue("{s:O,s:O}", "dtype", np_dt, "halo", hb)
      : Py_BuildValue("{s:O}", "dtype", np_dt);
  PyObject* obj = must(PyObject_Call(cls, args, kwargs),
                       "distributed_vector(...)");
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_XDECREF(hb);
  Py_DECREF(np_dt);
  Py_DECREF(cls);
  return vector(this, obj, n, dt);
}

vector session::make_vector_blocks(
    const std::vector<std::size_t>& sizes, dtype dt) {
  if (dt == dtype::f64 && !impl_->x64_enabled())
    fail("make_vector_blocks: dtype::f64 requested but JAX x64 is "
         "disabled — the device buffer would silently be f32; enable "
         "x64 (JAX_ENABLE_X64=1 before session construction) or use "
         "dtype::f32");
  std::size_t n = 0;
  for (std::size_t s : sizes) n += s;
  PyObject* szl = must(PyList_New((Py_ssize_t)sizes.size()),
                       "sizes list");
  for (std::size_t i = 0; i < sizes.size(); ++i)
    PyList_SET_ITEM(szl, (Py_ssize_t)i, PyLong_FromSize_t(sizes[i]));
  PyObject* cls = must(
      PyObject_GetAttrString(impl_->dr, "distributed_vector"),
      "distributed_vector");
  PyObject* np_dt = must(
      PyObject_GetAttrString(impl_->np, np_name(dt)), "numpy dtype");
  PyObject* args = Py_BuildValue("(n)", (Py_ssize_t)n);
  PyObject* kwargs = Py_BuildValue("{s:O,s:O}", "dtype", np_dt,
                                   "distribution", szl);
  PyObject* obj = must(PyObject_Call(cls, args, kwargs),
                       "distributed_vector(distribution=...)");
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(np_dt);
  Py_DECREF(cls);
  Py_DECREF(szl);
  return vector(this, obj, n, dt);
}

dense_matrix session::make_dense(std::size_t m, std::size_t n,
                                 const std::vector<double>& row_major) {
  PyObject* cls = must(PyObject_GetAttrString(impl_->dr, "dense_matrix"),
                       "dense_matrix");
  PyObject* obj;
  if (row_major.empty()) {
    obj = must(PyObject_CallFunction(cls, "((nn))", (Py_ssize_t)m,
                                     (Py_ssize_t)n),
               "dense_matrix((m, n))");
  } else {
    if (row_major.size() != m * n) fail("make_dense: data size != m*n");
    PyObject* flat = impl_->np_f32(row_major);
    PyObject* arr = must(PyObject_CallMethod(flat, "reshape", "nn",
                                             (Py_ssize_t)m, (Py_ssize_t)n),
                         "reshape");
    obj = must(PyObject_CallMethod(cls, "from_array", "O", arr),
               "dense_matrix.from_array");
    Py_DECREF(arr);
    Py_DECREF(flat);
  }
  Py_DECREF(cls);
  return dense_matrix(this, obj, m, n);
}

sparse_matrix session::make_sparse_coo(
    std::size_t m, std::size_t n, const std::vector<std::int64_t>& rows,
    const std::vector<std::int64_t>& cols,
    const std::vector<double>& values) {
  if (rows.size() != cols.size() || rows.size() != values.size())
    fail("make_sparse_coo: triple lengths differ");
  PyObject* cls = must(PyObject_GetAttrString(impl_->dr, "sparse_matrix"),
                       "sparse_matrix");
  PyObject* ra = impl_->np_i64(rows);
  PyObject* ca = impl_->np_i64(cols);
  PyObject* va = impl_->np_f32(values);
  PyObject* obj = must(
      PyObject_CallMethod(cls, "from_coo", "(nn)OOO", (Py_ssize_t)m,
                          (Py_ssize_t)n, ra, ca, va),
      "sparse_matrix.from_coo");
  Py_DECREF(va);
  Py_DECREF(ca);
  Py_DECREF(ra);
  Py_DECREF(cls);
  return sparse_matrix(this, obj, m, n, values.size());
}

mdarray session::make_mdarray(const std::vector<std::size_t>& shape,
                              const std::vector<double>& row_major) {
  if (shape.empty()) fail("make_mdarray: shape must have rank >= 1");
  std::size_t total = 1;
  for (std::size_t s : shape) total *= s;
  PyObject* shp = must(PyTuple_New((Py_ssize_t)shape.size()),
                       "shape tuple");
  for (std::size_t i = 0; i < shape.size(); ++i)
    PyTuple_SET_ITEM(shp, (Py_ssize_t)i, PyLong_FromSize_t(shape[i]));
  PyObject* cls = must(
      PyObject_GetAttrString(impl_->dr, "distributed_mdarray"),
      "distributed_mdarray");
  PyObject* obj;
  if (row_major.empty()) {
    obj = must(PyObject_CallFunction(cls, "(O)", shp),
               "distributed_mdarray(shape)");
  } else {
    if (row_major.size() != total)
      fail("make_mdarray: data size != product(shape)");
    PyObject* flat = impl_->np_f32(row_major);
    PyObject* arr = must(PyObject_CallMethod(flat, "reshape", "O", shp),
                         "reshape");
    obj = must(PyObject_CallMethod(cls, "from_array", "O", arr),
               "distributed_mdarray.from_array");
    Py_DECREF(arr);
    Py_DECREF(flat);
  }
  Py_DECREF(cls);
  Py_DECREF(shp);
  return mdarray(this, obj, shape);
}

mdarray session::make_mdarray(std::size_t m, std::size_t n,
                              const std::vector<double>& row_major) {
  return make_mdarray(std::vector<std::size_t>{m, n}, row_major);
}

mdspan session::submdspan(
    const mdarray& a,
    const std::vector<std::pair<std::size_t, std::size_t>>& box) {
  if (box.size() != a.rank())
    fail("submdspan: box rank != array rank");
  std::vector<std::size_t> wshape(box.size());
  PyObject* args = must(PyTuple_New((Py_ssize_t)box.size()),
                        "slice tuple");
  for (std::size_t d = 0; d < box.size(); ++d) {
    auto [lo, hi] = box[d];
    if (lo > hi || hi > a.shape()[d])
      fail("submdspan: window out of bounds");
    wshape[d] = hi - lo;
    PyObject* plo = PyLong_FromSize_t(lo);
    PyObject* phi = PyLong_FromSize_t(hi);
    PyObject* sl = must(PySlice_New(plo, phi, nullptr), "slice");
    Py_DECREF(plo);
    Py_DECREF(phi);
    PyTuple_SET_ITEM(args, (Py_ssize_t)d, sl);
  }
  PyObject* fn = must(PyObject_GetAttrString((PyObject*)a.obj_,
                                             "submdspan"),
                      "submdspan attr");
  PyObject* obj = must(PyObject_CallObject(fn, args), "submdspan(...)");
  Py_DECREF(fn);
  Py_DECREF(args);
  return mdspan(this, obj, std::move(wshape));
}

// ------------------------------------------------------------ algorithms

double session::dot(const vector& a, const vector& b) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "dot", "OO",
                          (PyObject*)a.obj_, (PyObject*)b.obj_),
      "dot(a, b)");
  double v = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return v;
}

void session::transform(const vector& in, vector& out, const expr& op) {
  PyObject* fn = impl_->op(op, 1);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "transform", "OOO",
                          (PyObject*)in.obj_, (PyObject*)out.obj_, fn),
      "transform");
  Py_DECREF(r);
  Py_DECREF(fn);
}

void session::transform2(const vector& a, const vector& b, vector& out,
                         const expr& op) {
  PyObject* zv = must(
      PyObject_CallMethod(impl_->views, "zip", "OO",
                          (PyObject*)a.obj_, (PyObject*)b.obj_),
      "views.zip");
  PyObject* fn = impl_->op(op, 2);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "transform", "OOO", zv,
                          (PyObject*)out.obj_, fn),
      "transform(zip)");
  Py_DECREF(r);
  Py_DECREF(fn);
  Py_DECREF(zv);
}

void session::for_each(vector& v, const expr& op) {
  PyObject* fn = impl_->op(op, 1);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "for_each", "OO",
                          (PyObject*)v.obj_, fn),
      "for_each");
  Py_DECREF(r);
  Py_DECREF(fn);
}

double session::transform_reduce(const vector& v, const expr& op) {
  PyObject* fn = impl_->op(op, 1);
  PyObject* tr = must(
      PyObject_GetAttrString(impl_->dr, "transform_reduce"),
      "transform_reduce attr");
  PyObject* args = Py_BuildValue("(O)", (PyObject*)v.obj_);
  PyObject* kwargs = Py_BuildValue("{s:O}", "transform_op", fn);
  PyObject* r = must(PyObject_Call(tr, args, kwargs), "transform_reduce");
  double out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  Py_DECREF(kwargs);
  Py_DECREF(args);
  Py_DECREF(tr);
  Py_DECREF(fn);
  return out;
}

// -------------------------------------------- custom-op escape hatch

void session::transform(const vector& in, vector& out,
                        const custom_op& op) {
  PyObject* fn = impl_->op(op);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "transform", "OOO",
                          (PyObject*)in.obj_, (PyObject*)out.obj_, fn),
      "transform(custom)");
  Py_DECREF(r);
  Py_DECREF(fn);
}

void session::transform2(const vector& a, const vector& b, vector& out,
                         const custom_op& op) {
  PyObject* zv = must(
      PyObject_CallMethod(impl_->views, "zip", "OO",
                          (PyObject*)a.obj_, (PyObject*)b.obj_),
      "views.zip");
  PyObject* fn = impl_->op(op);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "transform", "OOO", zv,
                          (PyObject*)out.obj_, fn),
      "transform(zip, custom)");
  Py_DECREF(r);
  Py_DECREF(fn);
  Py_DECREF(zv);
}

void session::for_each(vector& v, const custom_op& op) {
  PyObject* fn = impl_->op(op);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "for_each", "OO",
                          (PyObject*)v.obj_, fn),
      "for_each(custom)");
  Py_DECREF(r);
  Py_DECREF(fn);
}

double session::transform_reduce(const vector& v, const custom_op& op) {
  PyObject* fn = impl_->op(op);
  PyObject* tr = must(
      PyObject_GetAttrString(impl_->dr, "transform_reduce"),
      "transform_reduce attr");
  PyObject* args = Py_BuildValue("(O)", (PyObject*)v.obj_);
  PyObject* kwargs = Py_BuildValue("{s:O}", "transform_op", fn);
  PyObject* r = must(PyObject_Call(tr, args, kwargs),
                     "transform_reduce(custom)");
  double out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  Py_DECREF(kwargs);
  Py_DECREF(args);
  Py_DECREF(tr);
  Py_DECREF(fn);
  return out;
}

namespace {
// v[lo:hi] as a Python subrange view (new reference)
PyObject* py_window(void* obj, std::size_t lo, std::size_t hi) {
  PyObject* plo = PyLong_FromSize_t(lo);
  PyObject* phi = PyLong_FromSize_t(hi);
  PyObject* sl = must(PySlice_New(plo, phi, nullptr), "slice");
  Py_DECREF(plo);
  Py_DECREF(phi);
  PyObject* w = must(PyObject_GetItem((PyObject*)obj, sl), "v[lo:hi]");
  Py_DECREF(sl);
  return w;
}
}  // namespace

void session::inclusive_scan(const vector& in, vector& out) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "inclusive_scan", "OO",
                          (PyObject*)in.obj_, (PyObject*)out.obj_),
      "inclusive_scan");
  Py_DECREF(r);
}

void session::exclusive_scan(const vector& in, vector& out, double init) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "exclusive_scan", "OOd",
                          (PyObject*)in.obj_, (PyObject*)out.obj_, init),
      "exclusive_scan");
  Py_DECREF(r);
}

void session::inclusive_scan(const vector& in, std::size_t ilo,
                             std::size_t ihi, vector& out,
                             std::size_t olo, std::size_t ohi) {
  if (ilo > ihi || ihi > in.size() || olo > ohi || ohi > out.size() ||
      ihi - ilo != ohi - olo)
    fail("inclusive_scan: bad windows");
  PyObject* iw = py_window(in.obj_, ilo, ihi);
  PyObject* ow = py_window(out.obj_, olo, ohi);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "inclusive_scan", "OO", iw, ow),
      "inclusive_scan(windows)");
  Py_DECREF(r);
  Py_DECREF(ow);
  Py_DECREF(iw);
}

void session::exclusive_scan(const vector& in, std::size_t ilo,
                             std::size_t ihi, vector& out,
                             std::size_t olo, std::size_t ohi,
                             double init) {
  if (ilo > ihi || ihi > in.size() || olo > ohi || ohi > out.size() ||
      ihi - ilo != ohi - olo)
    fail("exclusive_scan: bad windows");
  PyObject* iw = py_window(in.obj_, ilo, ihi);
  PyObject* ow = py_window(out.obj_, olo, ohi);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "exclusive_scan", "OOd", iw, ow,
                          init),
      "exclusive_scan(windows)");
  Py_DECREF(r);
  Py_DECREF(ow);
  Py_DECREF(iw);
}

namespace {
// dr.<name>(*args, descending=...) — the sort family's shared call
// shape (five call sites); returns the result as a NEW reference and
// consumes nothing (caller still owns args)
PyObject* call_descending(PyObject* dr, const char* name, PyObject* args,
                          bool descending) {
  PyObject* fn = must(PyObject_GetAttrString(dr, name), name);
  PyObject* kwargs = Py_BuildValue("{s:O}", "descending",
                                   descending ? Py_True : Py_False);
  PyObject* r = must(PyObject_Call(fn, args, kwargs), name);
  Py_DECREF(kwargs);
  Py_DECREF(fn);
  return r;
}
}  // namespace

void session::sort(vector& v, bool descending) {
  PyObject* args = Py_BuildValue("(O)", (PyObject*)v.obj_);
  Py_DECREF(call_descending(impl_->dr, "sort", args, descending));
  Py_DECREF(args);
}

void session::sort_by_key(vector& keys, vector& values, bool descending) {
  PyObject* args = Py_BuildValue("(OO)", (PyObject*)keys.obj_,
                                 (PyObject*)values.obj_);
  Py_DECREF(call_descending(impl_->dr, "sort_by_key", args, descending));
  Py_DECREF(args);
}

void session::sort(vector& v, std::size_t lo, std::size_t hi,
                   bool descending) {
  if (lo > hi || hi > v.size()) fail("sort: window out of bounds");
  PyObject* w = py_window(v.obj_, lo, hi);
  PyObject* args = Py_BuildValue("(O)", w);
  Py_DECREF(call_descending(impl_->dr, "sort", args, descending));
  Py_DECREF(args);
  Py_DECREF(w);
}

void session::sort_by_key(vector& keys, std::size_t klo, std::size_t khi,
                          vector& values, std::size_t vlo,
                          std::size_t vhi, bool descending) {
  if (klo > khi || khi > keys.size() || vlo > vhi ||
      vhi > values.size() || khi - klo != vhi - vlo)
    fail("sort_by_key: bad windows");
  PyObject* kw = py_window(keys.obj_, klo, khi);
  PyObject* vw = py_window(values.obj_, vlo, vhi);
  PyObject* args = Py_BuildValue("(OO)", kw, vw);
  Py_DECREF(call_descending(impl_->dr, "sort_by_key", args,
                            descending));
  Py_DECREF(args);
  Py_DECREF(vw);
  Py_DECREF(kw);
}

bool session::is_sorted(const vector& v, std::size_t lo,
                        std::size_t hi) {
  if (lo > hi || hi > v.size()) fail("is_sorted: window out of bounds");
  PyObject* w = py_window(v.obj_, lo, hi);
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "is_sorted", "O", w),
      "is_sorted(window)");
  bool ok = PyObject_IsTrue(r) == 1;
  Py_DECREF(r);
  Py_DECREF(w);
  return ok;
}

vector session::argsort(const vector& v, bool descending) {
  PyObject* args = Py_BuildValue("(O)", (PyObject*)v.obj_);
  PyObject* obj = call_descending(impl_->dr, "argsort", args,
                                  descending);
  Py_DECREF(args);
  return vector(this, obj, v.size());
}

bool session::is_sorted(const vector& v) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "is_sorted", "O",
                          (PyObject*)v.obj_),
      "is_sorted");
  int t = PyObject_IsTrue(r);
  Py_DECREF(r);
  return t == 1;
}

void session::gemv(vector& c, const sparse_matrix& a, const vector& b) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "gemv", "OOO", (PyObject*)c.obj_,
                          (PyObject*)a.obj_, (PyObject*)b.obj_),
      "gemv");
  Py_DECREF(r);
}

void session::gemm(const dense_matrix& a, const dense_matrix& b,
                   dense_matrix& out) {
  PyObject* r = must(
      PyObject_CallMethod(impl_->dr, "gemm", "OOO", (PyObject*)a.obj_,
                          (PyObject*)b.obj_, (PyObject*)out.obj_),
      "gemm");
  Py_DECREF(r);
}

void session::transpose(mdarray& out, const mdarray& in,
                        const std::vector<std::size_t>& axes) {
  PyObject* r;
  if (axes.empty()) {  // numpy default: reversed axes
    r = must(PyObject_CallMethod(impl_->dr, "transpose", "OO",
                                 (PyObject*)out.obj_, (PyObject*)in.obj_),
             "transpose");
  } else {
    if (axes.size() != in.rank())
      fail("transpose: axes rank != array rank");
    PyObject* ax = must(PyTuple_New((Py_ssize_t)axes.size()),
                        "axes tuple");
    for (std::size_t i = 0; i < axes.size(); ++i)
      PyTuple_SET_ITEM(ax, (Py_ssize_t)i, PyLong_FromSize_t(axes[i]));
    r = must(PyObject_CallMethod(impl_->dr, "transpose", "OOO",
                                 (PyObject*)out.obj_, (PyObject*)in.obj_,
                                 ax),
             "transpose(axes)");
    Py_DECREF(ax);
  }
  Py_DECREF(r);
}

void session::save(const std::string& path, const vector& v) {
  PyObject* ckpt = must(PyObject_GetAttrString(impl_->dr, "checkpoint"),
                        "checkpoint module");
  PyObject* r = must(
      PyObject_CallMethod(ckpt, "save", "sO", path.c_str(),
                          (PyObject*)v.obj_),
      "checkpoint.save");
  Py_DECREF(r);
  Py_DECREF(ckpt);
}

vector session::load_vector(const std::string& path) {
  PyObject* ckpt = must(PyObject_GetAttrString(impl_->dr, "checkpoint"),
                        "checkpoint module");
  PyObject* obj = must(
      PyObject_CallMethod(ckpt, "load", "s", path.c_str()),
      "checkpoint.load");
  Py_DECREF(ckpt);
  // a checkpoint can hold any container kind; wrapping a matrix as a
  // vector would fail later with a confusing in-bridge error
  PyObject* cls = must(
      PyObject_GetAttrString(impl_->dr, "distributed_vector"),
      "distributed_vector");
  int is_vec = PyObject_IsInstance(obj, cls);
  Py_DECREF(cls);
  if (is_vec != 1) {
    Py_DECREF(obj);
    fail("load_vector: checkpoint does not hold a distributed_vector");
  }
  PyObject* len_obj = must(PyObject_CallMethod(obj, "__len__", nullptr),
                           "len(vector)");
  std::size_t n = PyLong_AsSize_t(len_obj);
  Py_DECREF(len_obj);
  return vector(this, obj, n);
}

void session::stencil_iterate(vector& a, vector& b,
                              const std::vector<double>& weights,
                              int steps) {
  PyObject* w = PyList_New((Py_ssize_t)weights.size());
  for (Py_ssize_t i = 0; i < (Py_ssize_t)weights.size(); ++i)
    PyList_SetItem(w, i, PyFloat_FromDouble(weights[i]));
  PyObject* r = must(
      PyObject_CallMethod(impl_->stencil_mod, "stencil_iterate", "OOOi",
                          (PyObject*)a.obj_, (PyObject*)b.obj_, w, steps),
      "stencil_iterate");
  // stencil_iterate returns the buffer holding the final state; callers
  // keep using `a` as "current" — swap handles if needed
  if (r == (PyObject*)b.obj_) std::swap(a.obj_, b.obj_);
  Py_DECREF(r);
  Py_DECREF(w);
}

// ------------------------------------------------------------ handles

namespace detail {
handle::~handle() { Py_XDECREF((PyObject*)obj_); }

handle::handle(handle&& o) noexcept : sess_(o.sess_), obj_(o.obj_) {
  o.obj_ = nullptr;
}

handle& handle::operator=(handle&& o) noexcept {
  if (this != &o) {
    Py_XDECREF((PyObject*)obj_);
    sess_ = o.sess_;
    obj_ = o.obj_;
    o.obj_ = nullptr;
  }
  return *this;
}
}  // namespace detail

void vector::iota(double start) {
  PyObject* r = must(
      PyObject_CallMethod(sess_->impl_->dr, "iota", "Od",
                          (PyObject*)obj_, start),
      "iota");
  Py_DECREF(r);
}

void vector::fill(double value) {
  PyObject* r = must(
      PyObject_CallMethod(sess_->impl_->dr, "fill", "Od",
                          (PyObject*)obj_, value),
      "fill");
  Py_DECREF(r);
}

double vector::reduce() const {
  PyObject* r = must(
      PyObject_CallMethod(sess_->impl_->dr, "reduce", "O",
                          (PyObject*)obj_),
      "reduce");
  double v = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return v;
}

void vector::halo_exchange() {
  PyObject* h = must(
      PyObject_CallMethod(sess_->impl_->dr, "halo", "O", (PyObject*)obj_),
      "halo(v)");
  PyObject* r = must(PyObject_CallMethod(h, "exchange", nullptr),
                     "exchange()");
  Py_DECREF(r);
  Py_DECREF(h);
}

std::vector<double> vector::to_host() const {
  PyObject* arr = must(
      PyObject_CallMethod(sess_->impl_->dr, "to_numpy", "O",
                          (PyObject*)obj_),
      "to_numpy");
  std::vector<double> out = sess_->impl_->to_host_f64(arr);
  Py_DECREF(arr);
  return out;
}

std::vector<double> dense_matrix::to_host() const {
  PyObject* arr = must(
      PyObject_CallMethod((PyObject*)obj_, "materialize", nullptr),
      "materialize");
  std::vector<double> out = sess_->impl_->to_host_f64(arr);
  Py_DECREF(arr);
  return out;
}

std::vector<double> mdarray::to_host() const {
  PyObject* arr = must(
      PyObject_CallMethod((PyObject*)obj_, "materialize", nullptr),
      "materialize");
  std::vector<double> out = sess_->impl_->to_host_f64(arr);
  Py_DECREF(arr);
  return out;
}

std::vector<double> mdspan::to_host() const {
  PyObject* arr = must(
      PyObject_CallMethod((PyObject*)obj_, "materialize", nullptr),
      "materialize");
  std::vector<double> out = sess_->impl_->to_host_f64(arr);
  Py_DECREF(arr);
  return out;
}

}  // namespace thp
