# Repo-level entry points.  The native library keeps its own Makefile
# (make -C native test / bridge-test).

.PHONY: lint test sanitize sanitize-test native-test

# static invariant gate (docs/SPEC.md §13): exits non-zero on any
# non-baselined drlint finding
lint:
	python tools/drlint.py --check

test:
	python -m pytest tests/ -x -q

# the tier-1 suite with the runtime sanitizer armed (recompile budget,
# finite flush sweep, canon-portability of every dispatch key, and the
# §23 plansan layer: shadow verifier + serializability oracle)
sanitize-test:
	DR_TPU_SANITIZE=1 python -m pytest tests/ -x -q -m 'not slow'

# the full soundness gate (docs/SPEC.md §23.5): tier-1 under the armed
# runtime sanitizer PLUS the static half (drlint R0-R10) — the
# fuzz_crank SANITIZE arm and the PR checklist both run this
sanitize: sanitize-test lint

native-test:
	$(MAKE) -C native test
	$(MAKE) -C native bridge-test
