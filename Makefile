# Repo-level entry points.  The native library keeps its own Makefile
# (make -C native test / bridge-test).

.PHONY: lint test sanitize-test native-test

# static invariant gate (docs/SPEC.md §13): exits non-zero on any
# non-baselined drlint finding
lint:
	python tools/drlint.py --check

test:
	python -m pytest tests/ -x -q

# the tier-1 suite with the runtime sanitizer armed (recompile budget,
# finite flush sweep, canon-portability of every dispatch key)
sanitize-test:
	DR_TPU_SANITIZE=1 python -m pytest tests/ -x -q

native-test:
	$(MAKE) -C native test
	$(MAKE) -C native bridge-test
